"""Interprocedural collective-coherence analyzer (rule family ``CX4xx``).

Every consensus wire in the runtime — the fault-ladder vote, spill and
ckpt-commit epochs, the ``Code.SkewPlan``/``Code.TopoPlan`` plan hashes,
the drain and watermark votes — exists to enforce one discipline:

    *no rank-local control flow decides anything after a collective has
    been entered, and every plan vote dominates its first dependent
    collective.*

The TS1xx lint is intra-file and the JX2xx pass is per-builder; the
hazards that actually bite (a tainted branch between two collectives
three calls apart) are interprocedural.  This pass closes the gap with a
deliberately *static, jax-free* approximation:

1. **Call graph.**  Every top-level function and method across the
   analyzed tree is indexed by leaf name; call edges resolve by leaf.
   Each function is marked with whether it can *enter a data collective*
   (all_to_all / all_gather / psum wires that move table bytes) and
   whether it can *enter a consensus vote* (the ``pmax`` code wires in
   ``exec/recovery.py``).  Seeds come from three ground truths:

   * the jaxpr registry's builder declarations — ``declare_builder``
     call sites are harvested **statically** (no jax import) and a
     builder with a non-empty ``collectives`` set is a data-collective
     leaf (the pmax consensus wire builders are classed as consensus);
   * the known collective facades (``parallel/shuffle.exchange``,
     ``topo/exchange.two_hop``, the ``parallel/collectives`` table ops,
     ``process_allgather``) so single-file fixtures resolve without the
     full tree;
   * direct ``lax.<collective>`` primitive calls in a function body.

   The ``utils/host.py`` pull funnel is excluded from propagation: host
   pulls are collectives, but marking every operator that reads a count
   sidecar as "between collectives" would drown the signal (pull
   traffic is budgeted by JX204/RT303 instead).

2. **Taint.**  Values derived from rank-local sources are tracked
   through assignments and returns: ``process_index`` /
   ``jax.process_index``, injector state (``recovery.probe`` /
   ``maybe_inject`` / ``injected``), caught exceptions (``except X as
   e``), file IO (``open``, ``os.path`` probes, ``os.listdir`` /
   ``os.stat``), wall clock (``time.time`` / ``perf_counter`` /
   ``monotonic``), the SIGTERM latch (``preempt.requested``), and
   per-rank shapes off host pulls (``len(host_array(...))`` /
   ``host_array(...).shape`` — the pulled *values* are replicated by
   construction and stay clean).  A consensus call is a **sanitizer**:
   its result is rank-coherent by definition, an ``if`` whose test
   contains one is consensus-guarded, and a consensus vote *inside* a
   tainted arm is the sanctioned "vote on your local fault" pattern.

3. **Checks.**

   * **CX401** — a tainted ``if``/``while`` whose arms issue no data
     collectives, positioned after one data collective with another
     data collective following before any consensus vote.
   * **CX402** — a tainted branch whose arms issue *different* data
     collective sequences, or a data collective under a rank-local
     trip count (tainted ``while`` test / ``for`` iterable).
   * **CX403** — vote dominance: when a function contains both a plan
     vote and its dependent collective (skew → ``split_exchange``,
     topo → ``two_hop``, ckpt-commit → the ``os.replace`` manifest
     publish, drain → ``drain_abort``), the vote must precede the
     first dependent *and* sit on every path to it (its enclosing
     branch chain must be a prefix of the dependent's).  Functions
     with a dependent but no vote are out of scope for this
     under-approximation — the interprocedural pairing is covered by
     the TS115/TS116 facade rules.
   * **CX404** — an *untyped* raise (not a ``CylonError`` subclass,
     not ``recovery.make_fault``, not a bare re-raise) from an except
     handler or a tainted path, after a data collective with no
     consensus vote in between.

Known under-approximations (deliberate — the gate must stay quiet on
clean code): taint does not flow through call *arguments* into callee
parameters (only through returns); dominance treats ``try`` bodies as
transparent; call edges resolve by leaf name and skip a small set of
generic object-protocol names.  Suppression uses the shared TS grammar
(``# tracecheck: off[CX401]``) from :mod:`cylon_tpu.analysis.rules`.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from .rules import Finding, file_suppressed, is_suppressed, suppressions

# --------------------------------------------------------------------------
# seeds

#: jax.lax collective primitives that move data between ranks.  A call
#: like ``lax.all_to_all`` / ``jax.lax.psum`` (the dotted path must
#: mention ``lax``) marks the enclosing function as data-entering.
_LAX_COLLECTIVES = frozenset({
    "all_to_all", "all_gather", "psum", "pmax", "pmin", "pmean",
    "ppermute", "pshuffle",
})

#: the pmax consensus wires in exec/recovery.py — rank-coherent votes,
#: the sanctioned boundary between rank-local state and control flow.
CONSENSUS_LEAVES = frozenset({
    "consensus_code", "guard_consensus", "spill_consensus",
    "drain_consensus", "count_consensus", "ckpt_commit_consensus",
    "watermark_consensus", "_plan_hash_consensus", "skew_plan_consensus",
    "topo_plan_consensus", "ckpt_resume_consensus", "preempt_consensus",
    "_consensus_wire", "_ns_consensus", "_consensus_fn",
    # the exec/integrity audit facade's rank-coherent verbs: each
    # computes a REPLICATED fingerprint and votes it over the pmax wire
    # (fingerprint_consensus → _plan_hash_consensus) BEFORE any
    # raise/proceed decision — the facade contract lint rule TS118
    # scopes to exec/integrity — so a call site is a sanctioned
    # sanitizer between rank-local state and the next collective,
    # exactly like the wires it rides
    "fingerprint_consensus", "audit_table", "verify_exchange",
    "audit_restored_table",
})

#: collective facades resolvable without the full tree (single-file
#: fixtures, synthetic test modules).  In a whole-tree run these names
#: also resolve through the call graph; the list just guarantees the
#: classification is stable either way.
DATA_FACADE_LEAVES = frozenset({
    "exchange", "two_hop", "allgather_table", "gather_table",
    "bcast_table", "allreduce", "process_allgather", "split_exchange",
})

#: rank-local taint sources, matched on the call's leaf name.
SOURCE_LEAVES = frozenset({
    "process_index",                 # jax.process_index / process_index
    "probe", "maybe_inject", "injected",   # chaos injector state
    "perf_counter", "monotonic", "time_ns", "process_time",  # wall clock
    "open", "listdir", "stat", "scandir",  # file IO
})

#: sources that need a dotted qualifier (the bare leaf is too generic).
SOURCE_QUALIFIED = frozenset({
    "time.time", "os.path.exists", "os.path.isfile", "os.path.getsize",
    "os.path.getmtime", "os.path.islink", "preempt.requested",
})

#: host-pull funnels: ``len(host_array(...))`` / ``host_array(..).shape``
#: taints (per-rank shapes), the pulled values themselves do not.
_HOST_PULL_LEAVES = frozenset({"host_array", "device_get", "host_pull"})

#: leaf names too generic to resolve through the call graph (object
#: protocol / container noise — resolving ``f.close()`` to a window
#: sink's collective ``close`` would poison every file handle).
_GENERIC_LEAVES = frozenset({
    "close", "flush", "write", "read", "get", "put", "update", "reset",
    "clear", "copy", "items", "keys", "values", "append", "add", "pop",
    "extend", "join", "split", "run", "start", "stop", "send", "next",
})

#: CX403 vote-dominance contract: per plan kind, the vote wires (and
#: their facades) and the dependent-collective names whose shape the
#: vote decides.  ``os.replace`` is the ckpt two-phase manifest publish;
#: a dotted spec must match the full call path at a dot boundary.
VOTE_KINDS = {
    "skew": {
        "votes": frozenset({"skew_plan_consensus", "adopt"}),
        "deps": frozenset({"split_exchange", "skew_split_targets"}),
    },
    "topo": {
        "votes": frozenset({"topo_plan_consensus", "ensure_adopted"}),
        "deps": frozenset({"two_hop"}),
    },
    "ckpt": {
        "votes": frozenset({"ckpt_commit_consensus"}),
        "deps": frozenset({"os.replace"}),
    },
    "drain": {
        "votes": frozenset({"drain_consensus", "drain_requested"}),
        "deps": frozenset({"drain_abort"}),
    },
    # the preempt-DECISION vote (exec/scheduler._maybe_preempt): the
    # agreed victim must be flagged for its boundary drain only after
    # the vote — flagging from a rank-local choice would drain
    # different tenants per rank
    "preempt": {
        "votes": frozenset({"preempt_consensus"}),
        "deps": frozenset({"_begin_preempt_drain"}),
    },
}

#: fallback typed-status names (kept in sync with cylon_tpu/status.py;
#: the harvest below extends this with any CylonError subclass found in
#: the analyzed tree, so single-file runs still recognize the taxonomy).
DEFAULT_TYPED_STATUS = frozenset({
    "CylonError", "InvalidError", "PredictedResourceExhausted",
    "DeviceOOMError", "CapacityOverflowError", "RankDesyncError",
    "ResumableAbort", "CheckpointCorruptError", "CylonTypeError",
    "CylonKeyError", "CylonIndexError", "CylonIOError",
    "NotImplementedCylonError", "ExecutionError",
    "AdmissionTimeoutError", "RequeueOverflowError",
})

#: modules whose collectives never propagate to callers: the host-pull
#: funnel (budgeted by JX204/RT303, would mark every count-sidecar read
#: as "between collectives") and the rank-report diagnostics (their
#: allgather fires from watchdog/teardown paths that are rank-local by
#: design — a straggler report is the point).
_NO_PROPAGATE_SUFFIXES = (
    os.path.join("utils", "host.py"),
    os.path.join("obs", "comm.py"),
    os.path.join("obs", "rank_report.py"),
)

#: Python builtins: a *bare* call (``max(a, b)``) is the builtin and
#: never resolves through the call graph; a dotted call
#: (``series.max()``) may still resolve to a collective-entering method.
_PY_BUILTINS = frozenset({
    "max", "min", "sum", "abs", "len", "sorted", "any", "all", "map",
    "filter", "round", "hash", "id", "iter", "print", "repr", "str",
    "int", "float", "bool", "list", "dict", "set", "tuple", "type",
    "getattr", "setattr", "hasattr", "isinstance", "enumerate", "zip",
    "range", "format", "divmod",
})


# --------------------------------------------------------------------------
# small AST helpers

def _call_name(node: ast.Call) -> str:
    """Dotted name of the call target ('' when not a name chain)."""
    parts = []
    t = node.func
    while isinstance(t, ast.Attribute):
        parts.append(t.attr)
        t = t.value
    if isinstance(t, ast.Name):
        parts.append(t.id)
    elif not parts:
        return ""
    return ".".join(reversed(parts))


def _leaf(fname: str) -> str:
    return fname.rsplit(".", 1)[-1]


def _matches_spec(fname: str, spec: str) -> bool:
    """Dotted specs match at a dot boundary; bare specs match the leaf."""
    if "." in spec:
        return fname == spec or fname.endswith("." + spec)
    return _leaf(fname) == spec


def _calls_in(node: ast.AST):
    """Every ast.Call under ``node``, skipping nested function defs
    (their bodies execute at their own call sites, not here).  Lambda
    bodies are included — they are applied in place in this codebase
    (retry_io thunks, key functions)."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n is not node:
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def _names_in(node: ast.AST):
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            yield n.id


def _target_roots(target: ast.AST):
    """Root names bound by an assignment target (tuple-aware)."""
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _target_roots(elt)
    elif isinstance(target, ast.Starred):
        yield from _target_roots(target.value)
    elif isinstance(target, (ast.Attribute, ast.Subscript)):
        t = target
        while isinstance(t, (ast.Attribute, ast.Subscript)):
            t = t.value
        if isinstance(t, ast.Name):
            yield t.id


def _is_lax_collective(fname: str) -> bool:
    parts = fname.split(".")
    return parts[-1] in _LAX_COLLECTIVES and "lax" in parts[:-1]


# --------------------------------------------------------------------------
# static harvest of declare_builder(...) call sites (jax-free registry
# ground truth: the same declarations registry.collect() imports)

def _harvest_builders(tree: ast.Module):
    """Yield ``(builder_leaf, has_collectives)`` for every
    ``declare_builder(f"{__name__}._foo_fn", ..., collectives={...})``
    call at module level.  The first argument is an f-string whose
    literal tail names the builder (``._foo_fn`` or
    ``._foo_fn[variant]``)."""
    for call in ast.walk(tree):
        if not isinstance(call, ast.Call):
            continue
        if _leaf(_call_name(call)) != "declare_builder" or not call.args:
            continue
        name = None
        first = call.args[0]
        if isinstance(first, ast.JoinedStr):
            for part in first.values:
                if isinstance(part, ast.Constant) \
                        and isinstance(part.value, str) \
                        and part.value.startswith("."):
                    name = part.value[1:].split("[", 1)[0]
        elif isinstance(first, ast.Constant) and isinstance(first.value, str):
            name = first.value.rsplit(".", 1)[-1].split("[", 1)[0]
        if not name:
            continue
        has_coll = False
        for kw in call.keywords:
            if kw.arg == "collectives":
                v = kw.value
                if isinstance(v, (ast.Set, ast.List, ast.Tuple)):
                    has_coll = bool(v.elts)
                elif isinstance(v, ast.Call):   # frozenset({...})
                    has_coll = any(
                        isinstance(a, (ast.Set, ast.List, ast.Tuple))
                        and a.elts for a in v.args)
                else:
                    has_coll = not (isinstance(v, ast.Constant)
                                    and not v.value)
        yield name, has_coll


def _harvest_typed_status(trees) -> frozenset[str]:
    """Typed fault taxonomy: DEFAULT_TYPED_STATUS plus every class in
    the analyzed tree whose base chain reaches a known typed name."""
    typed = set(DEFAULT_TYPED_STATUS)
    classes = []     # (name, base leaf names)
    for tree in trees:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                bases = set()
                for b in node.bases:
                    if isinstance(b, ast.Name):
                        bases.add(b.id)
                    elif isinstance(b, ast.Attribute):
                        bases.add(b.attr)
                classes.append((node.name, bases))
    for _ in range(3):   # transitive closure, shallow hierarchies
        grew = False
        for name, bases in classes:
            if name not in typed and bases & typed:
                typed.add(name)
                grew = True
        if not grew:
            break
    return frozenset(typed)


# --------------------------------------------------------------------------
# function index + call graph

@dataclass
class FuncInfo:
    qualname: str                  # module-relative: Class.method / func
    path: str
    node: ast.AST
    leaf: str = ""
    calls: frozenset = frozenset()       # leaf names called anywhere in body
    has_lax: bool = False
    no_propagate: bool = False
    enters_data: bool = False
    enters_consensus: bool = False
    returns_tainted: bool = False


def _index_functions(path: str, tree: ast.Module, no_propagate: bool):
    """Top-level functions and class methods (nested defs excluded from
    the callee index — their bodies belong to the enclosing scan)."""
    out = []

    def add(node, prefix=""):
        qn = prefix + node.name
        leaves, has_lax = set(), False
        for call in _calls_in_body(node):
            fname = _call_name(call)
            if not fname:
                continue
            leaves.add(_leaf(fname))
            if _is_lax_collective(fname):
                has_lax = True
        out.append(FuncInfo(qualname=qn, path=path, node=node,
                            leaf=node.name, calls=frozenset(leaves),
                            has_lax=has_lax, no_propagate=no_propagate))

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            add(node)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    add(sub, prefix=node.name + ".")
    return out


def _calls_in_body(func_node: ast.AST):
    """Every call in a function INCLUDING nested defs/lambdas — used for
    call-graph propagation, where a builder's per-shard closure issuing
    ``lax.psum`` makes the builder itself collective-entering."""
    for n in ast.walk(func_node):
        if isinstance(n, ast.Call):
            yield n


# --------------------------------------------------------------------------
# per-function linear scan

@dataclass
class _Site:
    order: int
    line: int
    blocks: tuple
    fname: str


@dataclass
class _BranchCheck:
    line: int
    start: int                 # event order at branch entry
    end: int                   # event order after both arms
    names: tuple               # tainted names steering the branch
    kind: str                  # 'if' | 'while' | 'for'
    arm_seqs: tuple            # (body data seq, orelse data seq)
    arm_consensus: bool        # either arm votes → sanctioned
    in_loop_data: bool         # inside a loop whose body enters data


@dataclass
class _RaiseCheck:
    line: int
    order: int
    in_handler: bool
    on_tainted_path: bool
    expr_tainted: bool
    typed: bool
    bare: bool


class _FuncScan:
    """One linear pass over a function body: taint, entering events in
    program order, branch/raise candidates, CX403 vote/dep sites."""

    def __init__(self, analyzer, info: FuncInfo):
        self.an = analyzer
        self.info = info
        self.tainted: set[str] = set()
        self.order = 0
        self.events: list[tuple[int, str]] = []   # (order, 'data'|'consensus')
        self.branches: list[_BranchCheck] = []
        self.raises: list[_RaiseCheck] = []
        self.votes: dict[str, list[_Site]] = {k: [] for k in VOTE_KINDS}
        self.deps: dict[str, list[_Site]] = {k: [] for k in VOTE_KINDS}
        self.returns_tainted = False

    # -- classification ---------------------------------------------------

    def _classify(self, fname: str) -> str | None:
        return self.an.classify(fname)

    def _record_calls(self, expr, blocks):
        """Record entering events + vote/dep sites for every call in an
        expression (lambda bodies included, nested defs skipped)."""
        if expr is None:
            return
        for call in _calls_in(expr):
            fname = _call_name(call)
            if not fname:
                continue
            kind = self._classify(fname)
            self.order += 1
            if kind:
                self.events.append((self.order, kind))
            site = _Site(self.order, call.lineno, blocks, fname)
            for vk, spec in VOTE_KINDS.items():
                if any(_matches_spec(fname, s) for s in spec["votes"]):
                    self.votes[vk].append(site)
                if any(_matches_spec(fname, s) for s in spec["deps"]):
                    self.deps[vk].append(site)

    # -- taint ------------------------------------------------------------

    def _is_source_call(self, call: ast.Call) -> bool:
        fname = _call_name(call)
        if not fname:
            return False
        if _leaf(fname) in SOURCE_LEAVES:
            return True
        if any(_matches_spec(fname, q) for q in SOURCE_QUALIFIED):
            return True
        # returns-taint through the call graph (unambiguous leaves only)
        return self.an.returns_tainted(fname)

    def _expr_tainted(self, expr) -> bool:
        if expr is None:
            return False
        # a consensus vote anywhere in the expression sanitizes it
        for call in _calls_in(expr):
            fname = _call_name(call)
            if fname and self._classify(fname) == "consensus":
                return False
        for name in _names_in(expr):
            if name in self.tainted:
                return True
        for call in _calls_in(expr):
            if self._is_source_call(call):
                return True
            # per-rank shape off a host pull: len(pull(...)) / pull().shape
            if _leaf(_call_name(call)) == "len" and call.args:
                if self._has_host_pull(call.args[0]):
                    return True
        for n in ast.walk(expr):
            if isinstance(n, ast.Attribute) and n.attr in ("shape", "nbytes"):
                if self._has_host_pull(n.value):
                    return True
        return False

    @staticmethod
    def _has_host_pull(expr) -> bool:
        return any(_leaf(_call_name(c)) in _HOST_PULL_LEAVES
                   for c in _calls_in(expr))

    def _assign(self, targets, value):
        roots = [r for t in targets for r in _target_roots(t)]
        if value is not None and self._expr_tainted(value):
            self.tainted.update(roots)
        else:
            self.tainted.difference_update(roots)

    # -- arm summaries ----------------------------------------------------

    def _data_seq(self, stmts) -> tuple:
        """Ordered leaf names of data-entering calls in a block (nested
        compounds included, nested defs skipped)."""
        seq = []
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for call in _calls_in(stmt):
                fname = _call_name(call)
                if fname and self._classify(fname) == "data":
                    seq.append(_leaf(fname))
        return tuple(seq)

    def _has_consensus(self, stmts) -> bool:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for call in _calls_in(stmt):
                fname = _call_name(call)
                if fname and self._classify(fname) == "consensus":
                    return True
        return False

    def _block_enters_data(self, stmts) -> bool:
        return bool(self._data_seq(stmts))

    # -- the walk ---------------------------------------------------------

    def run(self):
        node = self.info.node
        self._scan(node.body, blocks=(), guard=False, taintpath=False,
                   in_handler=False, in_loop_data=False)
        return self

    def _scan(self, stmts, *, blocks, guard, taintpath, in_handler,
              in_loop_data):
        for stmt in stmts:
            self._scan_stmt(stmt, blocks=blocks, guard=guard,
                            taintpath=taintpath, in_handler=in_handler,
                            in_loop_data=in_loop_data)

    def _scan_stmt(self, stmt, *, blocks, guard, taintpath, in_handler,
                   in_loop_data):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.Assign):
            self._record_calls(stmt.value, blocks)
            self._assign(stmt.targets, stmt.value)
            return
        if isinstance(stmt, ast.AnnAssign):
            self._record_calls(stmt.value, blocks)
            self._assign([stmt.target], stmt.value)
            return
        if isinstance(stmt, ast.AugAssign):
            self._record_calls(stmt.value, blocks)
            if self._expr_tainted(stmt.value):
                self.tainted.update(_target_roots(stmt.target))
            return
        if isinstance(stmt, ast.Expr):
            self._record_calls(stmt.value, blocks)
            return
        if isinstance(stmt, ast.Return):
            self._record_calls(stmt.value, blocks)
            if stmt.value is not None and self._expr_tainted(stmt.value):
                self.returns_tainted = True
            return
        if isinstance(stmt, ast.Raise):
            self._record_calls(stmt.exc, blocks)
            self._raise(stmt, guard=guard, taintpath=taintpath,
                        in_handler=in_handler)
            return
        if isinstance(stmt, ast.If):
            self._branch(stmt, stmt.body, stmt.orelse, kind="if",
                         blocks=blocks, guard=guard, taintpath=taintpath,
                         in_handler=in_handler, in_loop_data=in_loop_data)
            return
        if isinstance(stmt, ast.While):
            self._branch(stmt, stmt.body, stmt.orelse, kind="while",
                         blocks=blocks, guard=guard, taintpath=taintpath,
                         in_handler=in_handler, in_loop_data=in_loop_data)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._for(stmt, blocks=blocks, guard=guard, taintpath=taintpath,
                      in_handler=in_handler, in_loop_data=in_loop_data)
            return
        if isinstance(stmt, ast.Try):
            self._try(stmt, blocks=blocks, guard=guard, taintpath=taintpath,
                      in_handler=in_handler, in_loop_data=in_loop_data)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._record_calls(item.context_expr, blocks)
                if item.optional_vars is not None \
                        and self._expr_tainted(item.context_expr):
                    self.tainted.update(_target_roots(item.optional_vars))
            self._scan(stmt.body, blocks=blocks, guard=guard,
                       taintpath=taintpath, in_handler=in_handler,
                       in_loop_data=in_loop_data)
            return
        # default: record any calls in child expressions (assert, del, …)
        self._record_calls(stmt, blocks)

    def _branch(self, stmt, body, orelse, *, kind, blocks, guard,
                taintpath, in_handler, in_loop_data):
        self._record_calls(stmt.test, blocks)
        test_consensus = any(
            self._classify(_call_name(c)) == "consensus"
            for c in _calls_in(stmt.test) if _call_name(c))
        test_tainted = (not test_consensus
                        and self._expr_tainted(stmt.test))
        start = self.order
        arm_guard = guard or test_consensus
        arm_taint = taintpath or test_tainted
        body_in_loop = in_loop_data or (
            kind == "while" and self._block_enters_data(body))
        frame_base = (id(stmt), kind)
        self._scan(body, blocks=blocks + ((*frame_base, "body"),),
                   guard=arm_guard, taintpath=arm_taint,
                   in_handler=in_handler, in_loop_data=body_in_loop)
        saved = set(self.tainted)
        self._scan(orelse, blocks=blocks + ((*frame_base, "else"),),
                   guard=arm_guard, taintpath=arm_taint,
                   in_handler=in_handler, in_loop_data=in_loop_data)
        # merge: a name tainted on either arm stays tainted after the join
        self.tainted |= saved
        end = self.order
        if test_tainted and not guard:
            names = tuple(sorted(set(_names_in(stmt.test)) & self.tainted))
            body_seq = self._data_seq(body)
            else_seq = self._data_seq(orelse)
            if kind == "while" and body_seq:
                # rank-local trip count over a data collective
                self.branches.append(_BranchCheck(
                    stmt.lineno, start, end, names, kind,
                    (body_seq, ("<loop-exit>",)), False, in_loop_data))
            else:
                self.branches.append(_BranchCheck(
                    stmt.lineno, start, end, names, kind,
                    (body_seq, else_seq),
                    self._has_consensus(body) or self._has_consensus(orelse),
                    in_loop_data))

    def _for(self, stmt, *, blocks, guard, taintpath, in_handler,
             in_loop_data):
        self._record_calls(stmt.iter, blocks)
        iter_tainted = self._expr_tainted(stmt.iter) and not guard
        if self._expr_tainted(stmt.iter):
            self.tainted.update(_target_roots(stmt.target))
        else:
            self.tainted.difference_update(_target_roots(stmt.target))
        start = self.order
        body_seq = self._data_seq(stmt.body)
        self._scan(stmt.body, blocks=blocks + ((id(stmt), "for", "body"),),
                   guard=guard, taintpath=taintpath or iter_tainted,
                   in_handler=in_handler,
                   in_loop_data=in_loop_data or bool(body_seq))
        self._scan(stmt.orelse, blocks=blocks, guard=guard,
                   taintpath=taintpath, in_handler=in_handler,
                   in_loop_data=in_loop_data)
        if iter_tainted and body_seq:
            names = tuple(sorted(set(_names_in(stmt.iter)) & self.tainted))
            self.branches.append(_BranchCheck(
                stmt.lineno, start, self.order, names, "for",
                (body_seq, ("<loop-exit>",)), False, in_loop_data))

    def _try(self, stmt, *, blocks, guard, taintpath, in_handler,
             in_loop_data):
        # try body is transparent (executes unconditionally up to a
        # fault); handlers are branches and taint their bound name
        self._scan(stmt.body, blocks=blocks, guard=guard,
                   taintpath=taintpath, in_handler=in_handler,
                   in_loop_data=in_loop_data)
        for i, handler in enumerate(stmt.handlers):
            added = None
            if handler.name:
                self.tainted.add(handler.name)
                added = handler.name
            self._scan(handler.body,
                       blocks=blocks + ((id(stmt), "except", i),),
                       guard=guard, taintpath=taintpath, in_handler=True,
                       in_loop_data=in_loop_data)
            if added:
                self.tainted.discard(added)
        self._scan(stmt.orelse, blocks=blocks + ((id(stmt), "try", "else"),),
                   guard=guard, taintpath=taintpath, in_handler=in_handler,
                   in_loop_data=in_loop_data)
        self._scan(stmt.finalbody, blocks=blocks, guard=guard,
                   taintpath=taintpath, in_handler=in_handler,
                   in_loop_data=in_loop_data)

    def _raise(self, stmt, *, guard, taintpath, in_handler):
        exc = stmt.exc
        bare = exc is None or isinstance(exc, ast.Name)  # re-raise
        typed = False
        if isinstance(exc, ast.Call):
            ctor = _leaf(_call_name(exc))
            typed = ctor in self.an.typed_status or ctor == "make_fault"
        self.order += 1
        self.raises.append(_RaiseCheck(
            stmt.lineno, self.order, in_handler and not guard,
            taintpath and not guard,
            (not bare and exc is not None and self._expr_tainted(exc)
             and not guard),
            typed, bare))


# --------------------------------------------------------------------------
# the analyzer

@dataclass
class Report:
    """Outcome of a coherence run: suppression-filtered findings, the
    raw pre-suppression list (stale-suppression audit / --json), the
    CX403 verification summary (kind -> "path:line" of every vote site
    proven to dominate its first dependent collective), and the files
    analyzed."""
    findings: list[Finding] = field(default_factory=list)
    raw: list[Finding] = field(default_factory=list)
    vote_summary: dict = field(default_factory=dict)
    files: list[str] = field(default_factory=list)


class Analyzer:
    def __init__(self, files: dict[str, str]):
        self.files = files
        self.trees: dict[str, ast.Module] = {}
        self.functions: list[FuncInfo] = []
        self.by_leaf: dict[str, list[FuncInfo]] = {}
        self.data_builders: set[str] = set()
        self._syntax_errors: list[Finding] = []
        self._parse()
        self.typed_status = _harvest_typed_status(self.trees.values())
        self._propagate()
        self._classify_cache: dict[str, str | None] = {}

    # -- construction -----------------------------------------------------

    def _parse(self):
        for path, source in sorted(self.files.items()):
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError as e:
                self._syntax_errors.append(Finding(
                    "CX401", path, e.lineno or 0,
                    f"syntax error prevents coherence analysis: {e.msg}"))
                continue
            self.trees[path] = tree
            norm = path.replace("\\", "/")
            nop = any(norm.endswith(s.replace(os.sep, "/"))
                      for s in _NO_PROPAGATE_SUFFIXES)
            self.functions.extend(_index_functions(path, tree, nop))
            for name, has_coll in _harvest_builders(tree):
                if has_coll and name not in CONSENSUS_LEAVES:
                    self.data_builders.add(name)
        for fi in self.functions:
            self.by_leaf.setdefault(fi.leaf, []).append(fi)

    def _propagate(self):
        """Fixed point for enters_data / enters_consensus over leaf-name
        call edges."""
        for fi in self.functions:
            if fi.leaf in CONSENSUS_LEAVES:
                # consensus wires never count as data, even when their
                # builder body holds the lax pmax primitive
                fi.enters_consensus = True
                continue
            if fi.has_lax or fi.leaf in self.data_builders \
                    or fi.calls & DATA_FACADE_LEAVES \
                    or fi.calls & self.data_builders:
                fi.enters_data = True
            if fi.calls & CONSENSUS_LEAVES:
                fi.enters_consensus = True
        changed = True
        while changed:
            changed = False
            for fi in self.functions:
                if fi.leaf in CONSENSUS_LEAVES:
                    continue
                if fi.enters_data and fi.enters_consensus:
                    continue
                for leaf in fi.calls:
                    # calls are indexed by bare leaf here, so a builtin
                    # leaf can't be told apart from a dotted method —
                    # skip the edge (quiet direction)
                    if leaf in _GENERIC_LEAVES or leaf in _PY_BUILTINS:
                        continue
                    for callee in self.by_leaf.get(leaf, ()):
                        if callee.no_propagate or callee is fi:
                            continue
                        if callee.enters_data and not fi.enters_data:
                            fi.enters_data = changed = True
                        if callee.enters_consensus \
                                and not fi.enters_consensus:
                            fi.enters_consensus = changed = True

    # -- queries used by _FuncScan ---------------------------------------

    def classify(self, fname: str) -> str | None:
        """'data' | 'consensus' | None for a call target name."""
        if fname in self._classify_cache:
            return self._classify_cache[fname]
        leaf = _leaf(fname)
        out = None
        if leaf in CONSENSUS_LEAVES:
            out = "consensus"
        elif leaf in DATA_FACADE_LEAVES or leaf in self.data_builders \
                or _is_lax_collective(fname):
            out = "data"
        elif leaf not in _GENERIC_LEAVES \
                and not (leaf in _PY_BUILTINS and "." not in fname):
            cands = [f for f in self.by_leaf.get(leaf, ())
                     if not f.no_propagate]
            if cands:
                if any(f.enters_data for f in cands):
                    out = "data"
                elif any(f.enters_consensus for f in cands):
                    out = "consensus"
        self._classify_cache[fname] = out
        return out

    def returns_tainted(self, fname: str) -> bool:
        leaf = _leaf(fname)
        if leaf in _GENERIC_LEAVES:
            return False
        cands = self.by_leaf.get(leaf, ())
        return bool(cands) and all(f.returns_tainted for f in cands)

    # -- the run ----------------------------------------------------------

    def run(self) -> Report:
        # returns-taint fixpoint: scan everything, fold the returns-taint
        # bits back in, rescan until stable (shallow chains: 2-3 rounds)
        scans = {}
        for _ in range(5):
            scans = {fi.qualname + "@" + fi.path: _FuncScan(self, fi).run()
                     for fi in self.functions}
            changed = False
            for fi in self.functions:
                rt = scans[fi.qualname + "@" + fi.path].returns_tainted
                if rt != fi.returns_tainted:
                    fi.returns_tainted = rt
                    changed = True
            if not changed:
                break

        raw = list(self._syntax_errors)
        summary = {k: [] for k in VOTE_KINDS}
        for fi in self.functions:
            scan = scans[fi.qualname + "@" + fi.path]
            raw.extend(self._check_branches(fi, scan))
            raw.extend(self._check_raises(fi, scan))
            raw.extend(self._check_votes(fi, scan, summary))
        raw.sort(key=lambda f: (f.path, f.line, f.rule))

        findings = self._filter(raw)
        return Report(findings=findings, raw=raw, vote_summary=summary,
                      files=sorted(self.trees))

    # -- checks -----------------------------------------------------------

    def _check_branches(self, fi, scan):
        for b in scan.branches:
            who = ", ".join(b.names) if b.names else "a rank-local value"
            if b.arm_seqs[0] != b.arm_seqs[1]:
                if b.kind in ("while", "for"):
                    msg = (f"data collective {'/'.join(b.arm_seqs[0])} "
                           f"under a rank-local trip count ({who}) in "
                           f"{fi.qualname} — ranks can run different "
                           f"iteration counts and desync the sequence")
                else:
                    msg = (f"branch on {who} issues different collective "
                           f"sequences per arm "
                           f"({'/'.join(b.arm_seqs[0]) or 'none'} vs "
                           f"{'/'.join(b.arm_seqs[1]) or 'none'}) in "
                           f"{fi.qualname}")
                yield Finding("CX402", fi.path, b.line, msg)
                continue
            if b.arm_seqs[0]:
                continue    # identical non-empty sequences: coherent
            if b.arm_consensus:
                continue    # an arm votes: sanctioned realignment
            before = b.in_loop_data or any(
                k == "data" for o, k in scan.events if o <= b.start)
            if not before:
                continue
            nxt = next((k for o, k in scan.events if o > b.end), None)
            after = (nxt == "data") or (nxt is None and b.in_loop_data)
            if after:
                yield Finding(
                    "CX401", fi.path, b.line,
                    f"rank-local branch on {who} between two data "
                    f"collectives in {fi.qualname} with no intervening "
                    f"consensus vote")

    def _check_raises(self, fi, scan):
        for r in scan.raises:
            if r.bare or r.typed:
                continue
            if not (r.in_handler or r.on_tainted_path or r.expr_tainted):
                continue
            last_data = max((o for o, k in scan.events
                             if k == "data" and o < r.order), default=None)
            if last_data is None:
                continue
            if any(k == "consensus" for o, k in scan.events
                   if last_data < o < r.order):
                continue
            yield Finding(
                "CX404", fi.path, r.line,
                f"untyped rank-local raise in {fi.qualname} after a data "
                f"collective with no consensus vote in between — route "
                f"through recovery.make_fault / a CylonError subclass and "
                f"a consensus'd code")

    def _check_votes(self, fi, scan, summary):
        for kind, spec in VOTE_KINDS.items():
            deps, votes = scan.deps[kind], scan.votes[kind]
            if not deps or not votes:
                continue
            first = min(deps, key=lambda s: s.order)
            dominating = [v for v in votes if v.order < first.order
                          and v.blocks == first.blocks[:len(v.blocks)]]
            if dominating:
                summary[kind].append(f"{fi.path}:{dominating[0].line}")
            else:
                yield Finding(
                    "CX403", fi.path, votes[0].line,
                    f"{kind} plan vote ({votes[0].fname}) does not "
                    f"dominate its first dependent collective "
                    f"({first.fname}, line {first.line}) in "
                    f"{fi.qualname} — the vote must run before, and on "
                    f"every path to, the collective whose shape it "
                    f"decides")

    # -- suppression ------------------------------------------------------

    def def_spans(self, path: str):
        """(lineno, end_lineno) of every def in a file, nested included —
        a suppression on a def line covers its body."""
        tree = self.trees.get(path)
        if tree is None:
            return []
        return [(n.lineno, getattr(n, "end_lineno", n.lineno))
                for n in ast.walk(tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def _filter(self, raw):
        out = []
        sup_cache, span_cache, off_cache = {}, {}, {}
        for f in raw:
            src = self.files.get(f.path, "")
            if f.path not in off_cache:
                off_cache[f.path] = file_suppressed(src)
                sup_cache[f.path] = suppressions(src)
                span_cache[f.path] = self.def_spans(f.path)
            if off_cache[f.path]:
                continue
            def_lines = sorted((s for s, e in span_cache[f.path]
                                if s <= f.line <= e), reverse=True)
            if not is_suppressed(f, sup_cache[f.path], def_lines):
                out.append(f)
        return out


# --------------------------------------------------------------------------
# public entry points

def analyze_files(files: dict[str, str]) -> Report:
    """Run the coherence pass over in-memory sources (path -> source)."""
    return Analyzer(files).run()


def analyze_source(path: str, source: str) -> Report:
    return analyze_files({path: source})


def iter_py_files(paths):
    for root in paths:
        if os.path.isfile(root):
            yield root
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def analyze_paths(paths) -> Report:
    """Run the coherence pass over files/directories (whole-tree mode:
    the call graph spans every file, so interprocedural marks resolve)."""
    files = {}
    for path in iter_py_files(paths):
        with open(path, encoding="utf-8") as f:
            files[path] = f.read()
    return analyze_files(files)
