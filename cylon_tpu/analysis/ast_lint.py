"""Pass 1 — whole-package AST lint for trace-safety hazards.

Static source analysis, no jax import required.  The pass identifies the
*traced region* of each module — functions handed to ``shard_map`` or
``jax.jit`` (directly, via decorator, or transitively called from such a
function within the same module) — and flags:

* **TS101** host-sync calls inside the traced region (``np.asarray``,
  ``np.array``, ``jax.device_get``, ``host_array``/``host_arrays``/
  ``sync_pull``, ``.item()``/``.tolist()``, and ``float()``/``int()``/
  ``bool()`` on tracer-derived values): each forces a device→host pull
  per *call* once the surrounding trace escapes to eager, or a trace
  error inside jit — either way a silent serialization point;
* **TS102** Python ``if``/``while`` whose condition derives from a
  traced function's parameters (tracers): concretization error on TPU,
  or — worse — a silently rank-divergent branch on CPU test rigs.
  Conditions on factory-closure statics, ``x is (not) None`` tests, and
  shape/dtype/len-derived values are exempt;
* **TS103** ``jax.jit(f)`` call sites where ``f`` (a module-local def)
  uses a parameter in Python control flow but the jit wrapper declares
  no ``static_argnums``/``static_argnames`` — every distinct value
  retraces, every tracer crashes;
* **TS104** ``functools.lru_cache`` on a program builder taking a live
  ``Mesh`` parameter — the global cache pins the mesh (and its
  executables) forever; use
  :func:`cylon_tpu.utils.cache.program_cache`, which scopes the entry to
  the mesh's lifetime;
* **TS105** ``except`` handlers that classify OOM by string-matching
  (``"RESOURCE_EXHAUSTED" in str(e)`` and friends) outside
  ``exec/recovery.py`` — the typed fault taxonomy
  (:mod:`cylon_tpu.status`, ``exec/recovery.classify``) is the sanctioned
  classification boundary; ad-hoc matching forks the recovery decision
  away from the rank-coherent consensus ladder;
* **TS106** bare ``jax.device_put``/``jax.device_get`` of (lane-sized)
  arrays in ``relational/`` or ``parallel/`` modules — residency changes
  of operator state must go through the HBM ledger
  (:mod:`cylon_tpu.exec.memory`): an unaccounted upload skews every
  budget decision, and an unaccounted pull bypasses the spill tier's
  eviction bookkeeping AND the ``utils.host`` transfer funnel;
* **TS109** direct ledger admission/eviction calls
  (``ensure_headroom``/``try_free``/``spill_for_retry``/``evict_n``/
  ``evict_until``) anywhere outside ``exec/scheduler.py`` and
  ``exec/memory.py`` — admission must be SCHEDULER-mediated
  (:mod:`cylon_tpu.exec.scheduler` ``admit_allocation``/
  ``free_pressure``/``spill_retry``): a direct call bypasses per-tenant
  footprint attribution, admission-wait accounting and cross-tenant
  eviction bookkeeping, so the serving tier's budget decisions stop
  describing reality;
* **TS108** use-after-donate in ``relational/`` or ``exec/`` modules: a
  name passed at a *statically known* ``donate_argnums`` position (a
  ``jax.jit(..., donate_argnums=(...))`` wrapper, or a builder call
  carrying a constant-tuple ``donate=``/``donate_argnums=`` keyword —
  the ``(0,) if flag else ()`` conditional idiom counts) and then READ
  after the donating call: XLA aliased the buffer into the program's
  outputs, so the read observes freed or overwritten memory on device
  (and raises "Array has been deleted" at host access).  Rebinding or
  ``del`` clears the mark; donation flags whose positions are not
  statically visible (a variable ``donate=donate``) are not tracked —
  the rule under-approximates, like the rest of this pass.

* **TS111** reads of a *foreign* rank's checkpoint directory — a
  ``rank<r>`` path constructed off the checkpoint dir (``rank0``,
  ``f"rank{r}"``, …) in any module except ``exec/checkpoint.py``: the
  elastic re-shard path (``Stage.load_foreign_pieces``) is the one
  sanctioned cross-rank reader, because it sha-verifies every page,
  resolves the manifest GENERATION (a rewrite supersedes stale old-world
  dirs) and min-votes the adoption over the live mesh — an ad-hoc read
  can splice a stale generation's or a torn write's state in;

* **TS112** module-level mutable counter tables (``_STATS``-style dict
  literals) outside ``cylon_tpu/obs/`` — ad-hoc counter dicts fragment
  the telemetry the observability subsystem unified; counters route
  through the metrics registry facade (``cylon_tpu.obs.metrics``
  ``counter``/``group``/``namespace``), whose dict-like views are the
  sanctioned migration shim;

* **TS114** spill-file path construction or raw spill page file IO
  outside ``exec/memory.py`` — an ``open``/``np.save``/``np.load`` (or
  an ``os.path.join`` path build) naming a ``.spill`` page, a
  ``spill_dir`` variable or ``CYLON_TPU_SPILL_DIR``: the disk tier's
  pages carry IN-MEMORY sha256 hashes, take the bounded IO retry and
  count demote/promote traffic — ad-hoc page IO elsewhere skips all
  three, so a resume-era read could adopt a torn page and the ledger's
  residency picture stops describing reality (the disk-tier analog of
  TS106 for residency and TS107 for checkpoints);

* **TS115** skew-plan decisions outside the ``relational/skew.py`` plan
  facade — a call to the split-targets primitive
  (``skew_split_targets``), the plan vote (``skew_plan_consensus``) or
  the ``SkewPlan`` constructor, or an assignment to a plan's salted
  split-set fields (``fanout``/``chunk``/``start``/``home``/
  ``src_off``) anywhere else: the facade is what guarantees the
  finalize replication guard runs, the canonical plan hash covers every
  field that shapes the collective sequence, and the ``Code.SkewPlan``
  vote lands BEFORE the split's first exchange — an ad-hoc split or a
  post-vote salt mutation can put ranks into different exchange plans
  and silently void the stitched output's bit/order-equality contract;

* **TS116** topology decisions outside the ``cylon_tpu/topo`` plan
  facade — a call to the plan vote (``topo_plan_consensus``), the
  ``TopologyPlan`` constructor, or the tier/gateway primitives
  (``hop_counts``, ``gateway_of``), or an assignment to a plan's tier
  fields (``n_slices``/``ranks_per_slice``/``route``/``gateway``)
  anywhere else: the facade is what guarantees the slice map, gateway
  scheme and route choice feed ONE canonical plan hash voted
  (``Code.TopoPlan``) before the first hierarchical collective — an
  ad-hoc tier map or a post-vote mutation can put ranks into grouped
  collectives with different memberships, which deadlocks both tiers;

* **TS117** raw compilation entry points outside ``utils/cache.py`` and
  ``exec/compiler.py`` — a ``jax.jit``/``jax.pjit`` reference (as a
  call, a decorator or a ``partial`` argument; bare ``pjit`` included)
  or an AOT ``.lower(...).compile()`` chain anywhere else: every
  compile must ride the compile-lifecycle facade (``utils.cache.jit``
  deferring to ``exec/compiler.jit``, ``exec/compiler.aot_compile``)
  so the bounded compile ledger counts the executable, the
  compile-intent journal brackets the build (crash quarantine), the
  watchdog bounds its wall-clock and the persistent-cache manifest can
  hash-verify it — a raw jit is invisible to all four.  ``.compile()``
  is only flagged when its receiver is a ``.lower(...)`` call, so
  ``re.compile`` and friends never match;

* **TS118** integrity-audit decisions outside the ``exec/integrity``
  facade — a fingerprint primitive (``table_fingerprint``/
  ``partition_fingerprint``/``fingerprint_consensus``/the registered
  ``_fingerprint_fn`` builder) called directly from ``relational/``,
  ``parallel/`` or ``topo/``, or a ``DataIntegrityError``
  constructed/raised there: the facade's verb wrappers
  (``conserve_*``/``verify_*``/``audit_*``) are what guarantee the
  rank-coherent fingerprint vote lands BEFORE the raise/proceed
  decision — a rank that fingerprints or raises on its own can desert
  the others mid-collective — and that every check is counted into the
  audit stats whose armed-overhead contract the bench JSON reports;

* **TS110** streaming state transitions outside ``cylon_tpu/stream/``:
  a GroupBySink's private partial state written or list-mutated
  directly (``X._parts``/``X._regs``/``X._adopted``/``X._pending``) —
  bypassing the absorb/snapshot API desynchronizes every live
  incremental view's ``read()`` — or the window-lifetime ledger entry
  points (``register_window``/``evict_release``) called outside the
  stream package, bypassing the watermark close lifecycle
  (device → host → released) whose accounting the streaming bench's
  eviction deltas assert.  The defining modules (``exec/pipeline.py``,
  ``exec/memory.py``) are exempt by construction.

The pass is heuristic by design (a linter, not a verifier): it
under-approximates taint (module-local call graph only) and exempts
provably-static derivations; residual false positives are silenced with
``# tracecheck: off[RULE]`` (see :mod:`cylon_tpu.analysis.rules`).
"""

from __future__ import annotations

import ast
import os
import re

from .rules import Finding, file_suppressed, is_suppressed, suppressions

#: call names that ALWAYS host-sync (flagged anywhere in the traced region)
_HOST_SYNC_FUNCS = {"host_array", "host_arrays", "sync_pull"}
_NUMPY_MODULES = {"np", "numpy", "onp"}
_NUMPY_SYNC_ATTRS = {"asarray", "array", "ascontiguousarray"}
_METHOD_SYNCS = {"item", "tolist"}
_CAST_BUILTINS = {"float", "int", "bool", "complex"}

#: OOM message fragments whose use in an except handler is a TS105 finding
#: (keep in sync with exec/recovery._OOM_MARKERS — the sanctioned site)
_OOM_TEXT_MARKERS = ("resource_exhausted", "out of memory")
#: the one module allowed to string-match OOM text (path suffix)
_RECOVERY_MODULE = "exec/recovery.py"

#: directories whose modules may not change array residency directly
#: (TS106): all device_put/device_get of operator state goes through the
#: exec/memory HBM ledger
_RESIDENCY_DIRS = ("relational", "parallel")
_RESIDENCY_FUNCS = {"device_put", "device_get"}

#: the one module that may read ANOTHER rank's checkpoint directory
#: (TS111): the elastic re-shard path sha-verifies pages, resolves the
#: manifest generation and consensus-votes the adoption — everything an
#: ad-hoc cross-rank read would skip
_CKPT_SANCTIONED_FILE = "exec/checkpoint.py"
#: a string literal (incl. an f-string's literal part) naming a rank
#: directory: "rank0", "rank%d", the f"rank{r}" prefix, a joined
#: ".../rank3/..." segment
_RANK_DIR_LITERAL = re.compile(r"(^|/)rank(\d|\{|%|$)")

#: modules that may not write checkpoint artifacts directly (TS107):
#: relational/ operators and the pipelined range loop — all durable
#: state goes through exec/checkpoint.py (pages with content hashes,
#: two-phase rank-coherent manifest commit); that module is outside
#: these paths and therefore exempt by construction
_CKPT_PIPELINE_FILE = "exec/pipeline.py"
_CKPT_IO_LEAVES = {"save", "savez", "savez_compressed", "load",
                   "dump", "dumps", "loads"}

#: ledger admission/eviction entry points callable ONLY from the serving
#: scheduler or the ledger itself (TS109): admission is scheduler-
#: mediated so per-tenant footprints, admission waits and cross-tenant
#: evictions stay attributed in one place
_ADMISSION_FUNCS = {"ensure_headroom", "try_free", "spill_for_retry",
                    "evict_n", "evict_until"}
#: the two sanctioned modules (path suffixes)
_ADMISSION_OK_FILES = ("exec/scheduler.py", "exec/memory.py")

#: directories whose modules donate buffers through jitted programs
#: (TS108): the piece/join/sort builders and the pipelined range loop
_DONATE_DIRS = ("relational", "exec")
#: keyword names that declare donated positions on a builder/jit call
_DONATE_KWS = {"donate", "donate_argnums"}

#: streaming state owned by the stream package (TS110): a GroupBySink's
#: private partial-aggregate state — mutating it outside the sink's own
#: absorb/snapshot API desynchronizes every live streaming view's
#: ``read()`` from the rows actually absorbed — and the window-lifetime
#: ledger entry points, whose close lifecycle (device → host →
#: released) is what makes ``memory.stats()`` describe reality.  The
#: defining modules (exec/pipeline.py for the sink, exec/memory.py for
#: the ledger) are exempt by construction.
_SINK_STATE_ATTRS = {"_parts", "_regs", "_adopted", "_pending"}
_SINK_MUTATORS = {"append", "extend", "insert", "clear", "pop", "remove"}
_WINDOW_LIFETIME_FUNCS = {"register_window", "evict_release"}
_STREAM_OK_FILES = ("exec/pipeline.py", "exec/memory.py")

#: module-level mutable counter-table names (TS112): ad-hoc ``_STATS``
#: dicts and friends must route through the metrics registry facade
#: (cylon_tpu/obs/metrics — counter/group/namespace); the obs package
#: itself is the defining module and exempt by construction
_STATS_NAME_RE = re.compile(r"^_?[A-Z0-9_]*(STATS|COUNTERS|METRICS)$")

#: the one module that may construct spill-file paths or do raw spill
#: page IO (TS114): the disk tier (exec/memory) hashes every page,
#: wraps writes/reads in the bounded IO retry and counts the traffic —
#: ad-hoc page IO elsewhere skips all three
_SPILL_SANCTIONED_FILE = "exec/memory.py"
#: a ``.spill`` page-file segment in a string literal (the disk tier's
#: on-disk naming: ``<owner>.a<j>.s<k>.spill.npy``)
_SPILL_PAGE_RE = re.compile(r"\.spill(\.|$)")

#: the two modules that may call jax.jit/jax.pjit or chain
#: ``.lower(...).compile()`` directly (TS117): the cache-layer
#: re-export and the compile-lifecycle facade it defers to — every
#: other module compiles through them so the compile ledger, the
#: intent journal, the watchdog and the quarantine see every compile
_JIT_SANCTIONED_FILES = ("utils/cache.py", "exec/compiler.py")

#: plan-node stack primitives callable ONLY from the obs/plan.py
#: context-manager facade (TS113): an operator that calls push_node/
#: pop_node directly can leave the query-scoped node stack unbalanced —
#: every later operator in the query then parents under a dead node and
#: EXPLAIN trees stop matching the plan that actually ran.  Scoped to
#: the operator directories that push plan nodes.
_PLAN_STACK_FUNCS = {"push_node", "pop_node"}
_PLAN_DIRS = ("relational", "exec", "stream")
#: the defining package, matched as a QUALIFIED path pair (a workspace
#: directory that merely happens to be called "obs" must not disable
#: the rule for everything under it)
_OBS_PKG_PAIR = "/cylon_tpu/obs/"

#: skew-plan primitives callable ONLY from the relational/skew.py plan
#: facade (TS115): the facade owns split-set construction (detect →
#: finalize guard → canonical hash → Code.SkewPlan vote) and salt
#: assignment — a direct call elsewhere skips all of it
_SKEW_FACADE_FILE = "relational/skew.py"
_SKEW_PLAN_FUNCS = {"skew_split_targets", "skew_plan_consensus",
                    "SkewPlan"}
#: salted split-set fields of a SkewPlan no non-facade module may
#: assign (a post-vote mutation desyncs the voted plan hash)
_SKEW_PLAN_FIELDS = {"fanout", "chunk", "start", "home", "src_off"}

#: topology primitives callable ONLY from the cylon_tpu/topo plan
#: facade (TS116, mirroring TS115's shape): the facade owns slice-map
#: construction, the tier/gateway assignment (hop-count derivation is
#: where the gateway scheme is encoded) and the Code.TopoPlan vote —
#: a direct call elsewhere skips the canonical plan hash and the
#: pre-collective adoption vote.  Matched as a QUALIFIED path pair
#: like the obs package (a workspace directory that merely happens to
#: be called "topo" must not disable the rule).
_TOPO_PKG_PAIR = "/cylon_tpu/topo/"
_TOPO_PLAN_FUNCS = {"topo_plan_consensus", "TopologyPlan", "hop_counts",
                    "gateway_of"}
#: tier-map fields of a TopologyPlan no non-facade module may assign
#: (a post-vote mutation desyncs the voted plan hash and the grouped
#: collectives' membership)
_TOPO_PLAN_FIELDS = {"n_slices", "ranks_per_slice", "route", "gateway"}

#: integrity-audit primitives callable ONLY through the exec/integrity
#: facade's verb wrappers (TS118): the facade is where fingerprints are
#: computed over the registered (jaxpr-gated) builder, voted
#: rank-coherently BEFORE any raise/proceed decision, and counted into
#: the audit stats — an operator module that fingerprints or raises the
#: typed integrity fault directly can desync ranks (one raising while
#: the rest proceed) and bypasses the armed-overhead accounting the
#: bench contract reports.  Scoped to the operator directories; the
#: facade lives in exec/ and is exempt by construction.
_INTEGRITY_DIRS = ("relational", "parallel", "topo")
_INTEGRITY_FUNCS = {"table_fingerprint", "partition_fingerprint",
                    "fingerprint_consensus", "_fingerprint_fn"}

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "n_lanes", "cols",
                 "names", "ops"}
_STATIC_CALLS = {"len", "range", "enumerate", "zip", "isinstance", "getattr",
                 "hasattr", "tuple", "list", "str", "repr", "type"}


def _func_name(node: ast.expr) -> str:
    """Dotted name of a call target ('jax.jit' / 'shard_map' / ...)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_jit_name(name: str) -> bool:
    return name in ("jit", "jax.jit", "pjit", "jax.pjit")


def _is_raw_jit_name(name: str) -> bool:
    """A RAW (facade-bypassing) compilation name: dotted jax jit/pjit or
    bare ``pjit``.  Bare ``jit`` is NOT raw — that is the facade
    re-export operator modules bind from ``utils.cache``."""
    parts = name.split(".")
    return (name in ("jax.jit", "jax.pjit", "pjit")
            or (len(parts) > 1 and parts[0] == "jax"
                and parts[-1] in ("jit", "pjit")))


def _is_shard_map_name(name: str) -> bool:
    return name.split(".")[-1] == "shard_map"


def _is_lru_cache_deco(dec: ast.expr) -> bool:
    target = dec.func if isinstance(dec, ast.Call) else dec
    return _func_name(target).split(".")[-1] == "lru_cache"


def _has_mesh_param(fn: ast.FunctionDef) -> bool:
    for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
        if a.arg == "mesh":
            return True
        ann = a.annotation
        if ann is not None and "Mesh" in ast.unparse(ann):
            return True
    return False


class _Funcs(ast.NodeVisitor):
    """Index every def with its enclosing-def line chain."""

    def __init__(self):
        self.funcs: list[tuple[ast.FunctionDef, list[int]]] = []
        self._stack: list[int] = []

    def _visit_fn(self, node):
        self.funcs.append((node, list(reversed(self._stack))))
        self._stack.append(node.lineno)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn


def _param_names(fn) -> set[str]:
    args = fn.args
    names = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


def _names_in(node) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _target_roots(tgt) -> set[str]:
    """Names actually (re)bound by an assignment target: for ``a[i] = ...``
    only ``a`` (never the index ``i``); tuples/lists recurse."""
    if isinstance(tgt, ast.Name):
        return {tgt.id}
    if isinstance(tgt, (ast.Subscript, ast.Attribute)):
        return _target_roots(tgt.value)
    if isinstance(tgt, (ast.Tuple, ast.List)):
        out = set()
        for e in tgt.elts:
            out |= _target_roots(e)
        return out
    if isinstance(tgt, ast.Starred):
        return _target_roots(tgt.value)
    return set()


def _static_params(fn, call: ast.Call | None) -> set[str]:
    """Parameter names declared static via static_argnums/static_argnames
    on a jit decorator (@partial(jax.jit, ...)) or a jit call site."""
    sources = []
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            sources.append(dec)
    if call is not None:
        sources.append(call)
    positional = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    out: set[str] = set()
    for src in sources:
        for kw in src.keywords:
            if kw.arg not in ("static_argnums", "static_argnames"):
                continue
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if not isinstance(v, ast.Constant):
                    continue
                if isinstance(v.value, int) and kw.arg == "static_argnums":
                    if 0 <= v.value < len(positional):
                        out.add(positional[v.value])
                elif isinstance(v.value, str):
                    out.add(v.value)
    return out


def _is_static_expr(node, tainted: set[str]) -> bool:
    """True when the expression provably does not carry tracer values:
    constants, untainted names, shape/dtype/len derivations, and
    compositions thereof."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return node.id not in tainted
    if isinstance(node, ast.Attribute):
        # .shape/.dtype/... of anything is static metadata
        if node.attr in _STATIC_ATTRS:
            return True
        return _is_static_expr(node.value, tainted)
    if isinstance(node, ast.Subscript):
        return (_is_static_expr(node.value, tainted)
                and _is_static_expr(node.slice, tainted))
    if isinstance(node, ast.Call):
        fname = _func_name(node.func)
        if fname.split(".")[-1] in _STATIC_CALLS:
            return True
        return False
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return all(_is_static_expr(e, tainted) for e in node.elts)
    if isinstance(node, ast.BoolOp):
        return all(_is_static_expr(v, tainted) for v in node.values)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand, tainted)
    if isinstance(node, ast.BinOp):
        return (_is_static_expr(node.left, tainted)
                and _is_static_expr(node.right, tainted))
    if isinstance(node, ast.Compare):
        # identity tests against None are always static
        if (len(node.ops) == 1
                and isinstance(node.ops[0], (ast.Is, ast.IsNot))):
            return True
        # `key in container` with a static key: statically-keyed dict/set
        # membership (ubiquitous for op dispatch); a tracer KEY still taints
        if (len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))
                and _is_static_expr(node.left, tainted)):
            return True
        return (_is_static_expr(node.left, tainted)
                and all(_is_static_expr(c, tainted)
                        for c in node.comparators))
    if isinstance(node, ast.IfExp):
        return all(_is_static_expr(e, tainted)
                   for e in (node.test, node.body, node.orelse))
    return False


class _ModuleLint:
    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.findings: list[Finding] = []
        idx = _Funcs()
        idx.visit(tree)
        self.funcs = idx.funcs
        self.by_name: dict[str, ast.FunctionDef] = {}
        for fn, _parents in self.funcs:
            # first binding wins; shadowed names are rare in practice
            self.by_name.setdefault(fn.name, fn)
        self.def_lines = {fn.name: parents for fn, parents in self.funcs}

    # -- traced-region discovery -----------------------------------------
    def traced_functions(self) -> tuple[set[str], set[str]]:
        """Returns (roots, traced): names of functions directly wrapped by
        shard_map/jit, and the transitive module-local closure."""
        roots: set[str] = set()
        self.wrap_calls: dict[str, ast.Call] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                fname = _func_name(node.func)
                if ((_is_shard_map_name(fname) or _is_jit_name(fname))
                        and node.args
                        and isinstance(node.args[0], ast.Name)
                        and node.args[0].id in self.by_name):
                    roots.add(node.args[0].id)
                    self.wrap_calls.setdefault(node.args[0].id, node)
        for fn, _parents in self.funcs:
            for dec in fn.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                dname = _func_name(target)
                if _is_jit_name(dname) or _is_shard_map_name(dname):
                    roots.add(fn.name)
                elif (isinstance(dec, ast.Call)
                      and dname.split(".")[-1] == "partial" and dec.args):
                    inner = _func_name(dec.args[0])
                    if _is_jit_name(inner) or _is_shard_map_name(inner):
                        roots.add(fn.name)
        # transitive closure over module-local calls
        calls: dict[str, set[str]] = {}
        for fn, _parents in self.funcs:
            called = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and isinstance(node.func,
                                                             ast.Name):
                    if node.func.id in self.by_name:
                        called.add(node.func.id)
            calls[fn.name] = called
        traced = set(roots)
        frontier = list(roots)
        while frontier:
            cur = frontier.pop()
            for callee in calls.get(cur, ()):
                if callee not in traced:
                    traced.add(callee)
                    frontier.append(callee)
        return roots, traced

    # -- taint ------------------------------------------------------------
    def _taint(self, fn: ast.FunctionDef, is_root: bool) -> set[str]:
        """Single forward pass: parameter-derived names, with static
        derivations (shape/dtype/len/None-tests) left clean."""
        if is_root:
            statics = _static_params(fn, self.wrap_calls.get(fn.name))
            tainted = set(_param_names(fn)) - statics
        else:
            tainted = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                if not _is_static_expr(node.value, tainted) \
                        and (_names_in(node.value) & tainted):
                    for tgt in node.targets:
                        tainted |= _target_roots(tgt)
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name) \
                        and (_names_in(node.value) & tainted):
                    tainted.add(node.target.id)
        return tainted

    # -- rules ------------------------------------------------------------
    def run(self) -> list[Finding]:
        roots, traced = self.traced_functions()
        for fn, parents in self.funcs:
            self._check_lru_mesh(fn)
            if fn.name in traced:
                self._check_traced_body(fn, fn.name in roots)
        self._check_jit_sites()
        self._check_oom_stringmatch()
        self._check_device_residency()
        self._check_ckpt_artifacts()
        self._check_use_after_donate()
        self._check_direct_admission()
        self._check_foreign_rank_read()
        self._check_stream_state()
        self._check_stats_dicts()
        self._check_plan_stack()
        self._check_spill_file_io()
        self._check_skew_plan()
        self._check_topo_plan()
        self._check_raw_jit()
        self._check_integrity_facade()
        return self.findings

    def _emit(self, rule: str, node, msg: str) -> None:
        self.findings.append(
            Finding(rule, self.path, getattr(node, "lineno", 0), msg))

    def _check_lru_mesh(self, fn: ast.FunctionDef) -> None:
        for dec in fn.decorator_list:
            if _is_lru_cache_deco(dec) and _has_mesh_param(fn):
                self._emit(
                    "TS104", fn,
                    f"builder '{fn.name}' is lru_cache'd on a live Mesh — "
                    "the global cache pins the mesh and its executables; "
                    "use cylon_tpu.utils.cache.program_cache")

    def _check_traced_body(self, fn: ast.FunctionDef, is_root: bool) -> None:
        tainted = self._taint(fn, is_root)
        # nested defs are visited as their own functions; don't re-walk
        for node in ast.iter_child_nodes(fn):
            self._walk_traced(node, fn, tainted, is_root)

    def _walk_traced(self, node, fn, tainted, is_root) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs analyzed separately
        if isinstance(node, (ast.If, ast.While)) and is_root:
            if not _is_static_expr(node.test, tainted):
                kind = "if" if isinstance(node, ast.If) else "while"
                self._emit(
                    "TS102", node.test,
                    f"Python `{kind}` on tracer-derived value inside "
                    f"traced '{fn.name}' — concretization error under "
                    "jit, rank-divergent control flow under shard_map")
        if isinstance(node, ast.Call):
            self._check_host_sync_call(node, fn, tainted, is_root)
        for child in ast.iter_child_nodes(node):
            self._walk_traced(child, fn, tainted, is_root)

    def _check_host_sync_call(self, node: ast.Call, fn, tainted,
                              is_root) -> None:
        fname = _func_name(node.func)
        leaf = fname.split(".")[-1]
        arg_taint = any((_names_in(a) & tainted) for a in node.args) \
            if is_root else bool(node.args)
        if fname == "jax.device_get" or leaf in _HOST_SYNC_FUNCS:
            self._emit(
                "TS101", node,
                f"host-sync call `{fname}` inside traced '{fn.name}' — "
                "device→host round-trip per call")
            return
        if isinstance(node.func, ast.Attribute):
            base = node.func.value
            if (isinstance(base, ast.Name) and base.id in _NUMPY_MODULES
                    and node.func.attr in _NUMPY_SYNC_ATTRS and arg_taint):
                self._emit(
                    "TS101", node,
                    f"`{fname}` on a traced value inside '{fn.name}' — "
                    "materializes the tracer on host (or fails to trace)")
                return
            if (node.func.attr in _METHOD_SYNCS and not node.args
                    and not _is_static_expr(base, tainted)):
                self._emit(
                    "TS101", node,
                    f"`.{node.func.attr}()` on a traced value inside "
                    f"'{fn.name}' — scalar host pull per call")
                return
        if (is_root and isinstance(node.func, ast.Name)
                and node.func.id in _CAST_BUILTINS and node.args
                and not _is_static_expr(node.args[0], tainted)):
            self._emit(
                "TS101", node,
                f"`{node.func.id}()` on a tracer inside '{fn.name}' — "
                "concretizes the value (host sync or trace error)")

    def _check_oom_stringmatch(self) -> None:
        """TS105: OOM classification by message text inside an ``except``
        handler — sanctioned only in the recovery module, which owns the
        typed fault taxonomy and the consensus retry ladder."""
        if self.path.replace(os.sep, "/").endswith(_RECOVERY_MODULE):
            return
        seen: set[tuple[int, int]] = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Compare) and len(sub.ops) == 1
                        and isinstance(sub.ops[0], (ast.In, ast.NotIn))):
                    continue
                left = sub.left
                if (isinstance(left, ast.Constant)
                        and isinstance(left.value, str)
                        and any(m in left.value.lower()
                                for m in _OOM_TEXT_MARKERS)):
                    # nested handlers re-walk inner trees: one finding
                    # per Compare node, not per enclosing handler
                    key = (sub.lineno, sub.col_offset)
                    if key in seen:
                        continue
                    seen.add(key)
                    self._emit(
                        "TS105", sub,
                        f"except handler classifies OOM by string-matching "
                        f"({left.value!r}) — use the typed fault taxonomy "
                        "(cylon_tpu.exec.recovery.classify / is_oom); "
                        "ad-hoc matching bypasses the rank-coherent "
                        "recovery ladder")

    def _check_device_residency(self) -> None:
        """TS106: a bare ``jax.device_put``/``jax.device_get`` (or the
        bare imported name) inside a ``relational/`` or ``parallel/``
        module changes array residency behind the HBM ledger's back —
        every upload/eviction of operator state must go through
        :mod:`cylon_tpu.exec.memory` (which is outside these directories
        and therefore exempt by construction)."""
        parts = self.path.replace(os.sep, "/").split("/")
        if not any(d in parts for d in _RESIDENCY_DIRS):
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _func_name(node.func)
            leaf = fname.split(".")[-1]
            if leaf in _RESIDENCY_FUNCS and fname in (
                    leaf, f"jax.{leaf}", f"_jax.{leaf}"):
                self._emit(
                    "TS106", node,
                    f"`{fname}` changes array residency outside the HBM "
                    "ledger — route uploads/evictions through "
                    "cylon_tpu.exec.memory (register/evict/"
                    "upload_window) so budget and spill decisions stay "
                    "accounted and rank-coherent")

    def _check_ckpt_artifacts(self) -> None:
        """TS107: a direct ``open``/``np.save``/``np.load``/``pickle.*``
        of a checkpoint-directory path (``CYLON_TPU_CKPT_DIR`` or a
        ckpt-named derivation of it) inside ``relational/`` or
        ``exec/pipeline.py`` — durable artifacts written outside
        :mod:`cylon_tpu.exec.checkpoint` carry no content hash and skip
        the two-phase rank-coherent manifest commit, so a resume could
        restore torn or rank-divergent state."""
        norm = self.path.replace(os.sep, "/")
        parts = norm.split("/")
        if not ("relational" in parts or norm.endswith(_CKPT_PIPELINE_FILE)):
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _func_name(node.func)
            leaf = fname.split(".")[-1]
            root = fname.split(".")[0]
            is_io = (fname == "open"
                     or (leaf in _CKPT_IO_LEAVES
                         and root in _NUMPY_MODULES | {"jnp", "pickle"}))
            if is_io and _mentions_ckpt_path(node):
                self._emit(
                    "TS107", node,
                    f"`{fname}` writes/reads a checkpoint artifact outside "
                    "exec/checkpoint.py — durable piece state must go "
                    "through the checkpoint Stage (content-hashed pages, "
                    "two-phase rank-coherent manifest commit); a direct "
                    "artifact has no hash and no commit epoch, so resume "
                    "could restore torn or rank-divergent state")

    def _check_foreign_rank_read(self) -> None:
        """TS111: a ``rank<r>`` checkpoint path constructed off the ckpt
        dir anywhere outside ``exec/checkpoint.py``.  Rank directories
        are that module's private on-disk layout: the re-shard path
        reads foreign dirs under per-page sha verification, a manifest
        GENERATION scan (a post-reshard rewrite supersedes stale
        old-world dirs) and the min-consensus resume vote.  A direct
        cross-rank read — `os.path.join(ckpt_dir, f"rank{r}", ...)` and
        friends — sees none of that and can splice a stale generation's
        or torn write's state into a resume."""
        norm = self.path.replace(os.sep, "/")
        if norm.endswith(_CKPT_SANCTIONED_FILE):
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if _mentions_ckpt_path(node) and _mentions_rank_dir(node):
                self._emit(
                    "TS111", node,
                    f"`{_func_name(node.func)}` constructs a rank<r> "
                    "checkpoint path outside exec/checkpoint.py — "
                    "foreign rank directories may only be read by the "
                    "elastic re-shard path (Stage.load_foreign_pieces), "
                    "which sha-verifies pages, resolves the manifest "
                    "generation and consensus-votes the adoption")

    def _check_direct_admission(self) -> None:
        """TS109: a direct call of a ledger admission/eviction entry
        point (`ensure_headroom`/`try_free`/`spill_for_retry`/`evict_n`/
        `evict_until`) outside the serving scheduler and the ledger
        module itself — admission must be scheduler-mediated
        (exec/scheduler.admit_allocation / free_pressure / spill_retry)
        so the multi-tenant serving tier's footprint attribution,
        admission-wait accounting and cross-tenant eviction bookkeeping
        see every decision (docs/serving.md)."""
        norm = self.path.replace(os.sep, "/")
        if norm.endswith(_ADMISSION_OK_FILES):
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = _func_name(node.func).split(".")[-1]
            if leaf in _ADMISSION_FUNCS:
                self._emit(
                    "TS109", node,
                    f"`{_func_name(node.func)}` calls a ledger admission/"
                    "eviction entry point directly — admission must be "
                    "scheduler-mediated (cylon_tpu.exec.scheduler."
                    "admit_allocation / free_pressure / spill_retry) so "
                    "per-tenant footprints, admission waits and cross-"
                    "tenant evictions stay attributed and rank-coherent")

    def _check_stream_state(self) -> None:
        """TS110: streaming state transitions outside the stream package
        — (a) a write (or list mutation) of a GroupBySink's private
        partial state (``X._parts`` / ``X._regs`` / ``X._adopted`` /
        ``X._pending``) bypasses the absorb/snapshot API that keeps a
        live view's ``read()`` consistent with the rows absorbed; (b) a
        call of the window-lifetime ledger entry points
        (``register_window`` / ``evict_release``) bypasses the
        watermark-close lifecycle (device → host → released) that drains
        the ledger.  Sanctioned: ``cylon_tpu/stream/`` plus the defining
        modules (exec/pipeline.py, exec/memory.py)."""
        norm = self.path.replace(os.sep, "/")
        if "stream" in norm.split("/") or norm.endswith(_STREAM_OK_FILES):
            return
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    if (isinstance(tgt, ast.Attribute)
                            and tgt.attr in _SINK_STATE_ATTRS):
                        self._emit(
                            "TS110", node,
                            f"write to sink partial state `.{tgt.attr}` "
                            "outside cylon_tpu/stream/ — mutate through "
                            "the GroupBySink absorb/snapshot API so live "
                            "streaming views stay consistent")
            if not isinstance(node, ast.Call):
                continue
            fname = _func_name(node.func)
            if fname.split(".")[-1] in _WINDOW_LIFETIME_FUNCS:
                self._emit(
                    "TS110", node,
                    f"`{fname}` manages window-lifetime ledger state "
                    "outside cylon_tpu/stream/ — window buffers are "
                    "registered at append and retired by the watermark "
                    "close (device → host → released); a direct call "
                    "bypasses that lifecycle's eviction accounting")
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in _SINK_MUTATORS
                  and isinstance(node.func.value, ast.Attribute)
                  and node.func.value.attr in _SINK_STATE_ATTRS):
                self._emit(
                    "TS110", node,
                    f"mutation of sink partial state "
                    f"`.{node.func.value.attr}.{node.func.attr}()` "
                    "outside cylon_tpu/stream/ — route through the "
                    "GroupBySink absorb/snapshot API")

    def _check_stats_dicts(self) -> None:
        """TS112: a module-level mutable counter table — a dict literal
        (or bare ``dict()`` call) bound to a ``_STATS``-style name
        (``*STATS`` / ``*COUNTERS`` / ``*METRICS``) — anywhere outside
        ``cylon_tpu/obs/``.  Before the observability subsystem, four
        such dicts plus hand-rolled bench collection blocks each carried
        a private slice of the telemetry; the registry facade
        (cylon_tpu.obs.metrics ``counter``/``group``/``namespace``) is
        now the one place counters live, so Prometheus exposition, JSON
        snapshots and the bench detail see every counter.  Registry-
        backed views (``metrics.group(...)``) bound to the same names
        are the sanctioned migration shim and are not flagged (the
        rule keys on the mutable LITERAL, not the name alone)."""
        if _OBS_PKG_PAIR in "/" + self.path.replace(os.sep, "/"):
            return
        for node in self.tree.body:
            if isinstance(node, ast.AnnAssign):
                targets = [node.target] if node.value is not None else []
                value = node.value
            elif isinstance(node, ast.Assign):
                targets = node.targets
                value = node.value
            else:
                continue
            is_mutable_dict = isinstance(value, (ast.Dict, ast.DictComp)) \
                or (isinstance(value, ast.Call)
                    and _func_name(value.func) == "dict")
            if not is_mutable_dict:
                continue
            for tgt in targets:
                if (isinstance(tgt, ast.Name)
                        and _STATS_NAME_RE.match(tgt.id)):
                    self._emit(
                        "TS112", node,
                        f"module-level mutable counter table `{tgt.id}` "
                        "outside cylon_tpu/obs/ — route counters through "
                        "the metrics registry facade (cylon_tpu.obs."
                        "metrics counter/group/namespace) so Prometheus "
                        "exposition, JSON snapshots and bench_detail see "
                        "every counter (docs/observability.md)")

    def _check_plan_stack(self) -> None:
        """TS113: a direct ``push_node``/``pop_node`` call in
        ``relational/``, ``exec/`` or ``stream/`` — plan nodes must open
        through the obs/plan.py context-manager facade
        (``plan.node(...)`` / ``plan.annotate(...)``), whose balanced
        __enter__/__exit__ is what keeps the query-scoped node stack
        consistent across typed-fault unwinds and the recovery ladder's
        retries.  The defining module (cylon_tpu/obs/plan.py) is exempt
        by construction (it sits outside the scoped directories)."""
        parts = self.path.replace(os.sep, "/").split("/")
        if not any(d in parts for d in _PLAN_DIRS):
            return
        if _OBS_PKG_PAIR in "/" + self.path.replace(os.sep, "/"):
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _func_name(node.func)
            if fname.split(".")[-1] in _PLAN_STACK_FUNCS:
                self._emit(
                    "TS113", node,
                    f"`{fname}` manipulates the plan-node stack directly "
                    "— open plan nodes through the cylon_tpu.obs.plan "
                    "context-manager facade (plan.node(...) / "
                    "plan.annotate(...)) so the query-scoped stack stays "
                    "balanced across typed-fault unwinds "
                    "(docs/trace_safety.md)")

    def _check_spill_file_io(self) -> None:
        """TS114: spill-file path construction or raw spill page IO
        anywhere outside ``exec/memory.py`` — an IO call
        (``open``/``np.save``/``np.load``/pickle) or an
        ``os.path.join``-style path build whose argument subtree names a
        ``.spill`` page, a ``spill_dir`` variable/attribute or the
        ``CYLON_TPU_SPILL_DIR`` env var.  The disk tier's pages are only
        safe behind the ledger facade: content-hashed at demote,
        sha-verified at promote, written/read under the bounded IO
        retry, and counted in the demote/promote traffic — a direct
        page read can adopt a torn write, and a direct page write is
        invisible to the residency accounting (docs/trace_safety.md)."""
        norm = self.path.replace(os.sep, "/")
        if norm.endswith(_SPILL_SANCTIONED_FILE):
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _func_name(node.func)
            leaf = fname.split(".")[-1]
            root = fname.split(".")[0]
            is_io = (fname == "open"
                     or (leaf in _CKPT_IO_LEAVES
                         and root in _NUMPY_MODULES | {"jnp", "pickle"}))
            if ((is_io or leaf == "join")
                    and _mentions_spill_path(node)):
                self._emit(
                    "TS114", node,
                    f"`{fname}` constructs or touches a spill page file "
                    "outside exec/memory.py — disk-tier pages are "
                    "content-hashed, retried and accounted only behind "
                    "the ledger facade (demote/promote_host/"
                    "upload_window); ad-hoc page IO can adopt a torn "
                    "write and skews the residency picture")

    def _check_skew_plan(self) -> None:
        """TS115: a skew-plan decision outside the relational/skew.py
        plan facade — the split-targets primitive, the plan vote or the
        ``SkewPlan`` constructor called directly, or a plan's salted
        split-set field assigned.  The facade is the one place where
        detection feeds the finalize replication guard, the canonical
        plan hash covers every collective-shaping field, and the
        ``Code.SkewPlan`` vote runs before the split's first exchange
        (docs/skew.md); the defining module is exempt by
        construction."""
        norm = self.path.replace(os.sep, "/")
        if norm.endswith(_SKEW_FACADE_FILE):
            return
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                fname = _func_name(node.func)
                if fname.split(".")[-1] in _SKEW_PLAN_FUNCS:
                    self._emit(
                        "TS115", node,
                        f"`{fname}` makes a skew-plan decision outside "
                        "the relational/skew.py plan facade — split-set "
                        "construction, salt assignment and the "
                        "Code.SkewPlan vote must go through "
                        "detect/finalize_or_none/adopt/split_exchange "
                        "so every rank enters ONE voted exchange plan "
                        "(docs/trace_safety.md, docs/skew.md)")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in tgts:
                    if (isinstance(t, ast.Attribute)
                            and t.attr in _SKEW_PLAN_FIELDS
                            and isinstance(t.value, ast.Name)
                            and "plan" in t.value.id.lower()):
                        self._emit(
                            "TS115", node,
                            f"assignment to `{t.value.id}.{t.attr}` "
                            "mutates a SkewPlan's salted split set "
                            "outside the relational/skew.py facade — a "
                            "post-vote mutation desyncs the canonical "
                            "plan hash the ranks agreed on "
                            "(docs/trace_safety.md, docs/skew.md)")

    def _check_topo_plan(self) -> None:
        """TS116: a topology decision outside the cylon_tpu/topo plan
        facade — the plan vote, the ``TopologyPlan`` constructor, the
        hop-count/gateway primitives called directly, or a plan's tier
        fields assigned.  The facade is the one place where slice
        discovery feeds one canonical plan hash and the
        ``Code.TopoPlan`` vote runs before the first hierarchical
        collective (docs/topology.md); the defining package is exempt
        by construction, matched as a qualified path pair like
        obs/ (TS113)."""
        if _TOPO_PKG_PAIR in "/" + self.path.replace(os.sep, "/"):
            return
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                fname = _func_name(node.func)
                if fname.split(".")[-1] in _TOPO_PLAN_FUNCS:
                    self._emit(
                        "TS116", node,
                        f"`{fname}` makes a topology decision outside "
                        "the cylon_tpu/topo plan facade — slice-map "
                        "construction, gateway assignment and the "
                        "Code.TopoPlan vote must go through "
                        "topology/hier_plan/ensure_adopted/two_hop so "
                        "every rank routes ONE voted hop plan "
                        "(docs/trace_safety.md, docs/topology.md)")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in tgts:
                    if (isinstance(t, ast.Attribute)
                            and t.attr in _TOPO_PLAN_FIELDS
                            and isinstance(t.value, ast.Name)
                            and ("topo" in t.value.id.lower()
                                 or "plan" in t.value.id.lower())):
                        self._emit(
                            "TS116", node,
                            f"assignment to `{t.value.id}.{t.attr}` "
                            "mutates a TopologyPlan's tier map outside "
                            "the cylon_tpu/topo facade — a post-vote "
                            "mutation desyncs the canonical plan hash "
                            "and the grouped collectives' membership "
                            "(docs/trace_safety.md, docs/topology.md)")

    def _check_integrity_facade(self) -> None:
        """TS118: fingerprint computation or a typed integrity fault
        raised outside the exec/integrity audit facade — the fingerprint
        primitives (``table_fingerprint``/``partition_fingerprint``/
        ``fingerprint_consensus``/the registered ``_fingerprint_fn``
        builder) called directly from an operator module, or a
        ``DataIntegrityError`` constructed/raised there.  The facade's
        verb wrappers (``conserve_*``/``verify_*``/``audit_*``) are what
        guarantee the rank-coherent consensus vote lands BEFORE the
        raise/proceed decision (a rank that raises alone deserts the
        others mid-collective) and that every check lands in the audit
        stats the bench overhead contract reports.  Scoped to the
        operator directories (relational/, parallel/, topo/); exec/ —
        where the facade and the recovery ladder live — is exempt."""
        parts = self.path.replace(os.sep, "/").split("/")
        if not any(d in parts for d in _INTEGRITY_DIRS):
            return
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                fname = _func_name(node.func)
                last = fname.split(".")[-1]
                if last in _INTEGRITY_FUNCS:
                    self._emit(
                        "TS118", node,
                        f"`{fname}` computes or votes a content "
                        "fingerprint outside the exec/integrity audit "
                        "facade — fingerprints must go through the "
                        "facade's verb wrappers (verify_exchange/"
                        "audit_table/audit_restored_table) so the "
                        "rank-coherent vote precedes the raise/proceed "
                        "decision and the check is counted "
                        "(docs/trace_safety.md, docs/robustness.md)")
                elif last == "DataIntegrityError":
                    self._emit(
                        "TS118", node,
                        "`DataIntegrityError` constructed outside the "
                        "exec/integrity audit facade — an operator "
                        "module that raises the typed integrity fault "
                        "directly skips the consensus vote, so one rank "
                        "can abort while the rest proceed into the next "
                        "collective (docs/trace_safety.md)")
            elif isinstance(node, ast.Raise):
                exc = node.exc
                if isinstance(exc, ast.Name) \
                        and exc.id == "DataIntegrityError":
                    self._emit(
                        "TS118", node,
                        "`raise DataIntegrityError` outside the "
                        "exec/integrity audit facade — see the facade's "
                        "verb wrappers (docs/trace_safety.md)")

    def _check_use_after_donate(self) -> None:
        """TS108: a name passed at a statically-known donated position
        and read after the donating call (see module docstring).  Scans
        each function body in statement order: statement N's loads are
        checked against donations recorded by statements < N, so the
        donating call's own arguments never self-flag."""
        parts = self.path.replace(os.sep, "/").split("/")
        if not any(d in parts for d in _DONATE_DIRS):
            return
        for fn, _parents in self.funcs:
            self._scan_donate_fn(fn)

    def _scan_donate_fn(self, fn) -> None:
        donating: dict[str, tuple] = {}   # callable name -> positions
        donated: dict[str, int] = {}      # buffer name -> donating line

        def mark_call_args(call: ast.Call, positions: tuple) -> None:
            if any(isinstance(a, ast.Starred) for a in call.args):
                return  # positions unresolvable past a *splat
            for p in positions:
                if p < len(call.args) and isinstance(call.args[p], ast.Name):
                    donated.setdefault(call.args[p].id, call.lineno)

        def stmt_bound(st) -> set:
            bound: set[str] = set()
            for node in ast.walk(st):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        bound |= _target_roots(tgt)
                elif isinstance(node, (ast.AugAssign, ast.For)):
                    bound |= _target_roots(node.target)
                elif isinstance(node, ast.Delete):
                    for tgt in node.targets:
                        bound |= _target_roots(tgt)
            return bound

        for st in _linear_stmts(fn.body):
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs scanned as their own functions
            # 0. a COMPOUND statement that rebinds a donated name clears
            # the mark BEFORE its loads are checked: a `for buf in ...`
            # target binds before the body reads it, so flagging those
            # reads would be a false positive — the read-then-rebind
            # ordering inside one compound is not statically resolvable,
            # and this pass under-approximates (never false-flags)
            if not isinstance(st, (ast.Assign, ast.AugAssign, ast.Expr,
                                   ast.Return, ast.Delete)):
                for name in stmt_bound(st):
                    donated.pop(name, None)
            # 1. loads of already-donated names.  Metadata-only reads
            # (`buf.shape`, `buf.dtype`, ... — _STATIC_ATTRS) are exempt
            # like everywhere else in this pass: jax keeps the aval on a
            # deleted Array, so they never touch the donated buffer.
            meta_reads = {id(a.value) for a in ast.walk(st)
                          if isinstance(a, ast.Attribute)
                          and a.attr in _STATIC_ATTRS}
            for node in ast.walk(st):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and id(node) not in meta_reads
                        and node.id in donated):
                    self._emit(
                        "TS108", node,
                        f"`{node.id}` read after being donated at line "
                        f"{donated[node.id]} — donate_argnums aliased its "
                        "buffer into the donating program's outputs, so "
                        "this read observes freed/overwritten device "
                        "memory (rebind or drop the name instead)")
                    donated.pop(node.id, None)  # one finding per donation
            # 2. donations performed by this statement
            for node in ast.walk(st):
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Call):
                    # immediate apply: builder(..., donate=(..))(args)
                    ipos = _donated_positions(node.func)
                    if ipos is not None:
                        mark_call_args(node, ipos)
                elif (isinstance(node.func, ast.Name)
                      and node.func.id in donating):
                    mark_call_args(node, donating[node.func.id])
            for node in ast.walk(st):
                if not isinstance(node, ast.Assign):
                    continue
                positions = (_donated_positions(node.value)
                             if isinstance(node.value, ast.Call) else None)
                for tgt in node.targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    if positions is not None:
                        donating[tgt.id] = positions
                    else:
                        # rebound to a non-donating value: stale donate
                        # positions must not flag the new callable's args
                        donating.pop(tgt.id, None)
            # 3. (re)bindings and dels clear the donated mark
            for name in stmt_bound(st):
                donated.pop(name, None)

    def _check_raw_jit(self) -> None:
        """TS117: raw compilation entry points outside the
        compile-lifecycle facade — a ``jax.jit``/``jax.pjit`` reference
        (call, decorator, ``partial`` argument or alias; bare ``pjit``
        too) or an AOT ``.lower(...).compile()`` chain anywhere but
        ``utils/cache.py`` and ``exec/compiler.py``.  A raw jit's
        executable is invisible to the bounded compile ledger, its
        build is not bracketed by the crash-quarantine intent journal,
        no watchdog bounds it, and the persistent-cache manifest cannot
        hash-verify it (docs/robustness.md, docs/trace_safety.md).
        ``.compile()`` only matches when its receiver is a
        ``.lower(...)`` call, so ``re.compile``/``str.lower`` never
        trip it."""
        norm = self.path.replace(os.sep, "/")
        if norm.endswith(_JIT_SANCTIONED_FILES):
            return
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Attribute):
                name = _func_name(node)
                if _is_raw_jit_name(name):
                    self._emit(
                        "TS117", node,
                        f"raw `{name}` reference outside the compile-"
                        "lifecycle facade — compile through utils.cache."
                        "jit (exec/compiler.jit) or exec/compiler."
                        "aot_compile so the compile ledger, intent "
                        "journal, watchdog and quarantine see it")
            elif isinstance(node, ast.Call):
                func = node.func
                if (isinstance(func, ast.Name)
                        and _is_raw_jit_name(func.id)):
                    self._emit(
                        "TS117", node,
                        f"raw `{func.id}(...)` call outside the compile-"
                        "lifecycle facade — compile through utils.cache."
                        "jit (exec/compiler.jit) so the compile ledger, "
                        "intent journal, watchdog and quarantine see it")
                elif (isinstance(func, ast.Attribute)
                        and func.attr == "compile"
                        and isinstance(func.value, ast.Call)
                        and isinstance(func.value.func, ast.Attribute)
                        and func.value.func.attr == "lower"):
                    self._emit(
                        "TS117", node,
                        "raw `.lower(...).compile()` AOT chain outside "
                        "the compile-lifecycle facade — use exec/"
                        "compiler.aot_compile so the compile ledger, "
                        "intent journal, watchdog and quarantine see "
                        "the build")

    def _check_jit_sites(self) -> None:
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and _is_jit_name(_func_name(node.func))):
                continue
            if not (node.args and isinstance(node.args[0], ast.Name)):
                continue
            target = self.by_name.get(node.args[0].id)
            if target is None:
                continue
            kw = {k.arg for k in node.keywords}
            if kw & {"static_argnums", "static_argnames"}:
                continue
            params = _param_names(target)
            control_params = set()
            for sub in ast.walk(target):
                if isinstance(sub, (ast.If, ast.While)):
                    control_params |= (_names_in(sub.test) & params)
            if control_params:
                self._emit(
                    "TS103", node,
                    f"jax.jit({target.name}) without static_argnums, but "
                    f"param(s) {sorted(control_params)} drive Python "
                    "control flow — every call with a tracer there fails, "
                    "every distinct value retraces")


def _donated_positions(call: ast.Call) -> tuple | None:
    """Statically-known donated argument positions declared by a call: a
    ``donate=``/``donate_argnums=`` keyword whose value is a non-empty
    tuple/list of int constants (a single int counts; the
    ``(0,) if flag else ()`` conditional idiom resolves to its body).
    ``None`` when absent or not statically resolvable — those calls are
    not tracked (TS108 under-approximates)."""
    for kw in call.keywords:
        if kw.arg not in _DONATE_KWS:
            continue
        val = kw.value
        if isinstance(val, ast.IfExp):
            val = val.body
        elts = val.elts if isinstance(val, (ast.Tuple, ast.List)) else [val]
        pos = []
        for v in elts:
            if (isinstance(v, ast.Constant) and isinstance(v.value, int)
                    and not isinstance(v.value, bool)):
                pos.append(v.value)
            else:
                return None
        return tuple(pos) or None
    return None


def _linear_stmts(body: list):
    """Top-level statements of a function body in source order.  Each
    compound statement (if/loop/with/try) is processed as ONE unit by
    the TS108 scan: its loads are checked against donations recorded by
    *earlier* statements, then any donations inside it are recorded for
    the statements after it.  A compound that REBINDS a donated name
    (e.g. a for-loop target) clears the mark before its loads are
    checked — the read-vs-rebind ordering inside one block is not
    statically resolvable.  Donation→read sequences wholly inside one
    compound block are therefore missed (under-approximation), but a
    read can never be flagged against a donation that runs after it or
    against a binding that shadows the donated buffer."""
    return list(body)


def _mentions_rank_dir(node: ast.Call) -> bool:
    """Does the call's argument subtree contain a string literal naming
    a ``rank<r>`` directory segment?  f-strings contribute their literal
    parts (``f"rank{r}"`` → Constant ``"rank"``), so the common
    construction shapes are all covered; plain identifiers like
    ``rank`` variables are NOT flagged (the rule keys on the on-disk
    layout's literal, like TS107 keys on the ckpt-path mention)."""
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and _RANK_DIR_LITERAL.search(sub.value)):
            return True
    return False


def _mentions_spill_path(node: ast.Call) -> bool:
    """Does the call's argument subtree reference the disk tier's spill
    pages — a ``.spill`` page-file literal, a ``spill_dir``-named
    name/attribute, or the ``CYLON_TPU_SPILL_DIR`` env var?  Keyed on
    the on-disk naming like TS107/TS111, so ordinary uses of the word
    "spill" (``spill_events``, ``spill_consensus``, ``spilled``) never
    fire."""
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and ("CYLON_TPU_SPILL_DIR" in sub.value
                     or _SPILL_PAGE_RE.search(sub.value))):
            return True
        if isinstance(sub, ast.Name) and "spill_dir" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) \
                and "spill_dir" in sub.attr.lower():
            return True
    return False


def _mentions_ckpt_path(node: ast.Call) -> bool:
    """Does the call's argument subtree reference the checkpoint
    directory — the ``CYLON_TPU_CKPT_DIR`` env var or a ckpt-named
    name/attribute/constant derived from it?  Keeps TS107 targeted:
    ordinary np.save/open of non-checkpoint paths never fires."""
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and ("CKPT" in sub.value.upper()
                     or "CYLON_TPU_CKPT_DIR" in sub.value)):
            return True
        if isinstance(sub, ast.Name) and "ckpt" in sub.id.lower():
            return True
        if isinstance(sub, ast.Attribute) and "ckpt" in sub.attr.lower():
            return True
    return False


def lint_source_raw(path: str, source: str):
    """``(findings, def_spans)`` BEFORE suppression filtering — neither
    line/def comments nor the file-level ``tracecheck: off`` are
    applied.  ``def_spans`` is ``[(lineno, end_lineno), ...]`` for every
    function def, the map needed to apply (or audit) suppressions
    downstream: the gate's ``--audit-suppressions`` and ``--json``
    output both need the pre-suppression view."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("TS101", path, e.lineno or 0,
                        f"syntax error prevents linting: {e.msg}")], []
    lint = _ModuleLint(path, source, tree)
    raw = lint.run()
    spans = [(fn.lineno, getattr(fn, "end_lineno", fn.lineno))
             for fn, _parents in lint.funcs]
    return raw, spans


def enclosing_def_lines(spans, line: int) -> list[int]:
    """Def-statement lines of every span containing ``line`` (innermost
    first) — a suppression on a def covers its body."""
    return sorted((s for s, e in spans if s <= line <= e), reverse=True)


def lint_source(path: str, source: str) -> list[Finding]:
    if file_suppressed(source):
        return []
    raw, spans = lint_source_raw(path, source)
    sup = suppressions(source)
    return [f for f in raw
            if not is_suppressed(f, sup, enclosing_def_lines(spans, f.line))]


def lint_file(path: str) -> list[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(path, f.read())


def lint_paths(paths) -> list[Finding]:
    """Lint every ``.py`` file under the given files/directories."""
    findings = []
    for root in paths:
        if os.path.isfile(root):
            findings.extend(lint_file(root))
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    findings.extend(lint_file(os.path.join(dirpath, name)))
    return findings
