"""Rule registry + findings + suppression parsing for the trace-safety
analyzer.

Rule families (documented in ``docs/trace_safety.md``):

* ``TS1xx`` — AST lint (:mod:`cylon_tpu.analysis.ast_lint`), source-level
  hazards visible without tracing;
* ``JX2xx`` — jaxpr verification (:mod:`cylon_tpu.analysis.jaxpr_check`),
  SPMD invariants checked on the traced program;
* ``RT3xx`` — runtime sentinel (:mod:`cylon_tpu.analysis.runtime`),
  retrace / transfer budgets enforced during test sessions;
* ``CX4xx`` — interprocedural collective coherence
  (:mod:`cylon_tpu.analysis.coherence`), rank-local control flow
  positioned between collectives and plan-vote dominance.

Suppression: a trailing comment ``# tracecheck: off[TS101]`` (comma-
separated rule ids, or bare ``off`` for all rules) on the flagged line or
on the enclosing ``def`` line silences the finding; file-level ``#
tracecheck: off`` within the first five lines silences the whole file.
Suppressions are deliberate, reviewable artifacts — the linter never
auto-inserts them.
"""

from __future__ import annotations

import io
import re
import tokenize
from dataclasses import dataclass

RULES = {
    "TS101": "host-sync call reachable inside a traced (jit/shard_map) body",
    "TS102": "Python if/while on a tracer-derived value in a traced body",
    "TS103": "jax.jit wrapper missing static_argnums for a control param",
    "TS104": "lru_cache'd program builder keyed on a live Mesh object",
    "TS105": "except handler classifies OOM by string-matching outside the "
             "recovery module (the fault taxonomy is the sanctioned "
             "boundary)",
    "TS106": "bare jax.device_put/device_get in relational/ or parallel/ "
             "(residency changes must go through the exec/memory HBM "
             "ledger)",
    "TS107": "checkpoint artifact written outside exec/checkpoint.py "
             "(direct open/np.save/pickle of CYLON_TPU_CKPT_DIR paths "
             "bypasses the page-hash/two-phase-manifest protocol)",
    "TS108": "use-after-donate: an array read after being passed through "
             "a donate_argnums position in relational/ or exec/ (the "
             "donating call invalidated its buffer)",
    "TS109": "direct ledger admission/eviction call outside "
             "exec/scheduler.py and exec/memory.py (admission must be "
             "scheduler-mediated so multi-tenant footprints and "
             "cross-tenant evictions stay attributed)",
    "TS111": "foreign-rank checkpoint directory read outside "
             "exec/checkpoint.py (a rank<r> path constructed off the "
             "ckpt dir skips the re-shard path's sha verification, "
             "generation scan and resume consensus)",
    "TS110": "GroupBySink partials mutated or window-lifetime state "
             "registered/evicted outside cylon_tpu/stream/ (and the "
             "defining modules) — streaming state transitions must ride "
             "the sink absorb/snapshot API and the window close "
             "lifecycle so snapshots stay consistent and the ledger "
             "drains at close",
    "TS112": "module-level mutable counter dict (_STATS-style table) "
             "outside cylon_tpu/obs/ — counters must route through the "
             "metrics registry facade (cylon_tpu.obs.metrics) so "
             "exposition, snapshots and bench detail see every counter",
    "TS113": "plan-node push/pop outside the obs/plan.py context-manager "
             "facade in relational/, exec/ or stream/ — operators must "
             "open plan nodes via plan.node()/annotate(); a raw "
             "push_node/pop_node call can unbalance the query-scoped "
             "stack and reparent every later operator's tree",
    "TS114": "spill-file path construction or raw spill page IO outside "
             "exec/memory.py — disk-tier pages are content-hashed, "
             "written/read under the bounded IO retry and counted in "
             "the demote/promote traffic only behind the ledger facade; "
             "ad-hoc page IO can adopt a torn write and skews the "
             "residency accounting",
    "TS115": "skew-plan decision (split-set construction, salt "
             "assignment, split targets, plan vote) outside the "
             "relational/skew.py plan facade — an ad-hoc split skips "
             "the finalize guard, the canonical plan hash and the "
             "rank-coherent Code.SkewPlan vote, so ranks can enter "
             "different exchange plans and the stitched output loses "
             "its bit/order-equality contract",
    "TS116": "topology decision (TopologyPlan construction, "
             "tier/gateway assignment, hop-count derivation, plan "
             "vote) outside the cylon_tpu/topo facade — an ad-hoc "
             "tier map skips the canonical plan hash and the "
             "rank-coherent Code.TopoPlan vote, so ranks can route "
             "the same exchange over different hop plans and deadlock "
             "the grouped collectives",
    "TS117": "raw jax.jit/jax.pjit call (or .lower(...).compile() AOT "
             "chain) outside utils/cache.py and exec/compiler.py — "
             "compilation must ride the compile-lifecycle facade "
             "(exec/compiler.jit via utils.cache, aot_compile) so the "
             "compile ledger, intent journal, watchdog and quarantine "
             "see every compile; a raw jit is invisible to all four",
    "TS118": "fingerprint computation or DataIntegrityError raised "
             "outside the exec/integrity audit facade — operator "
             "modules must go through the facade's verb wrappers "
             "(conserve_*/verify_*/audit_*) so the rank-coherent "
             "fingerprint vote precedes the raise/proceed decision and "
             "every check lands in the audit stats; a rank that "
             "fingerprints or raises alone deserts the others "
             "mid-collective",
    "JX201": "collective under lax.cond/switch — rank-divergent deadlock",
    "JX202": "collective under data-dependent lax.while_loop",
    "JX203": "int32→int64 widening of a row-scale array under x64",
    "JX204": "host callback count exceeds the builder's budget",
    "JX205": "collective set differs from the builder's declaration",
    "CX401": "rank-local branch between two collectives without an "
             "intervening consensus vote — a value tainted by a "
             "rank-local source (process_index, injector state, caught "
             "exception, file IO, wall clock, per-rank host shapes) "
             "steers control flow after one collective has been entered "
             "and before the next, so ranks can disagree about what "
             "happens in between",
    "CX402": "path-dependent collective sequence — a branch or loop on a "
             "rank-local value issues different collectives on its arms "
             "(or a data collective under a rank-local trip count), so "
             "ranks can enter mismatched collective sequences and "
             "deadlock",
    "CX403": "plan/epoch vote does not dominate its first dependent "
             "collective — a Code.SkewPlan/TopoPlan/CkptCommit/"
             "PreemptDrain consensus vote must execute before (and on "
             "every path to) the first collective whose shape it "
             "decides",
    "CX404": "rank-local raise after a collective was entered without a "
             "consensus'd typed status — an untyped exception raised "
             "from an except handler or a tainted path desyncs ranks "
             "that already passed a collective; route it through the "
             "fault taxonomy (recovery.make_fault / CylonError "
             "subclasses) and a consensus vote",
    "RT301": "builder recompiled for an identical shape signature",
    "RT302": "builder compiled more distinct programs than its budget",
    "RT303": "op exceeded its declared host-transfer budget",
}


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


_SUPPRESS_RE = re.compile(
    r"#\s*tracecheck:\s*off(?:\[(?P<rules>[A-Z0-9,\s]+)\])?")


def _comment_lines(source: str) -> set[int] | None:
    """1-based line numbers holding a real ``#`` comment token, or None
    when the source does not tokenize — a docstring that merely MENTIONS
    the suppression grammar (like this module's) must not suppress
    anything or trip the stale-suppression audit."""
    lines: set[int] = set()
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                lines.add(tok.start[0])
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return None
    return lines


def suppressions(source: str) -> dict[int, set[str] | None]:
    """Per-line suppression map: line -> set of rule ids, or None = all.
    Line numbers are 1-based, matching ast/Finding."""
    comments = _comment_lines(source)
    out: dict[int, set[str] | None] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        if comments is not None and i not in comments:
            continue
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        ids = m.group("rules")
        out[i] = (None if ids is None
                  else {r.strip() for r in ids.split(",") if r.strip()})
    return out


def file_suppressed(source: str) -> bool:
    comments = _comment_lines(source)
    for i, text in enumerate(source.splitlines()[:5], start=1):
        if comments is not None and i not in comments:
            continue
        m = _SUPPRESS_RE.search(text)
        if m and m.group("rules") is None:
            return True
    return False


def is_suppressed(finding: Finding, sup: dict, def_lines: list[int]) -> bool:
    """``def_lines``: line numbers of enclosing function defs (innermost
    first) — a suppression on a def line covers its whole body."""
    for line in [finding.line, *def_lines]:
        rules = sup.get(line, "missing")
        if rules == "missing":
            continue
        if rules is None or finding.rule in rules:
            return True
    return False
