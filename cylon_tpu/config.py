"""Global configuration for cylon_tpu.

The reference framework is int64-first (Arrow/pandas default integer keys,
BASELINE.json's 1B int64-key join).  JAX defaults to 32-bit; we enable x64 at
import so device tables can faithfully hold pandas/Arrow int64/float64 columns.
Set ``CYLON_TPU_X64=0`` to opt out (columns will then be downcast on transfer).

Reference analog: the CMake/feature-flag + env-var config surface
(cpp/CMakeLists.txt:129-441, redis_ucx_ucc_oob_context.cpp:104-105) collapses
into this module plus per-op option dataclasses.
"""

from __future__ import annotations

import os

import jax

X64_ENABLED = os.environ.get("CYLON_TPU_X64", "1") != "0"
if X64_ENABLED:
    jax.config.update("jax_enable_x64", True)


def _env_flag(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v not in ("0", "false", "False", "")


#: Print [BENCH] timing lines (reference: CYLON_BENCH_TIMER, util/macros.hpp:102).
BENCH_TIMINGS = _env_flag("CYLON_TPU_BENCH", False)

#: Round variable capacities up to powers of two to bound recompilation.
POW2_CAPACITIES = _env_flag("CYLON_TPU_POW2_CAPS", True)


def pow2ceil(n: int) -> int:
    """Bucket a dynamic capacity to the next 1/8th-power-of-two step (exact
    powers of two below 16Ki).  Keeps the family of compiled shapes
    logarithmic (<= 8 buckets per octave) while bounding capacity overshoot
    to 12.5% — at tens of millions of rows, a full pow2 ceiling would waste
    up to 2x of every output-space pass."""
    n = max(int(n), 1)
    if not POW2_CAPACITIES:
        return n
    if n <= 16384:
        return 1 << (n - 1).bit_length()
    step = 1 << ((n - 1).bit_length() - 3)
    return -(-n // step) * step
