"""Global configuration for cylon_tpu.

The reference framework is int64-first (Arrow/pandas default integer keys,
BASELINE.json's 1B int64-key join).  JAX defaults to 32-bit; we enable x64 at
import so device tables can faithfully hold pandas/Arrow int64/float64 columns.
Set ``CYLON_TPU_X64=0`` to opt out (columns will then be downcast on transfer).

Reference analog: the CMake/feature-flag + env-var config surface
(cpp/CMakeLists.txt:129-441, redis_ucx_ucc_oob_context.cpp:104-105) collapses
into this module plus per-op option dataclasses.
"""

from __future__ import annotations

import os

import jax

# jax < 0.5 compatibility: ``shard_map`` graduated from
# ``jax.experimental.shard_map`` to ``jax.shard_map``; every module binds
# ``jax.shard_map`` at import time, and this module is imported first
# (package __init__ line 1), so the alias is in place before any binding.
if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map
    jax.shard_map = _shard_map

X64_ENABLED = os.environ.get("CYLON_TPU_X64", "1") != "0"
if X64_ENABLED:
    jax.config.update("jax_enable_x64", True)

# Persistent compiled-program cache: TPC-H-class workloads compile dozens
# of distinct programs and remote TPU compiles cost seconds-to-minutes
# each; the persistent cache makes every rerun warm (verified working over
# the axon remote-compile tunnel).  Opt out with CYLON_TPU_COMPILE_CACHE=0.
#
# CPU-only processes (JAX_PLATFORMS=cpu — the test rig, dryrun, multihost
# drivers) run UNCACHED: XLA:CPU executable (de)serialization segfaults
# nondeterministically (observed live across three full-suite runs, ~1%
# of compiles, crashing in put_executable_and_time /
# get_executable_and_time / backend_compile_and_load), and CPU compiles
# are fast enough not to need persistence.  The cache's value is the
# seconds-to-minutes remote TPU compiles, which stay cached.
#
# The directory is additionally suffixed with a host-CPU fingerprint:
# XLA:CPU AOT results bake in the COMPILE machine's feature set and the
# cache key does not capture it — loading an entry cached on a host with
# different features SIGILLs (observed live: `+prefer-no-gather` mismatch
# after a machine change).  That protects mixed-platform processes that
# do cache while making a machine change a cold start, not a crash.


def _cpu_only() -> bool:
    # the programmatic config value is authoritative: it folds in the
    # JAX_PLATFORMS env default AND any jax.config.update('jax_platforms')
    # a test conftest/driver issued before importing this package (the env
    # var alone lies under the axon sitecustomize, which exports
    # JAX_PLATFORMS=axon even for runs that then pin cpu)
    try:
        plats = jax.config.jax_platforms or ""
    except Exception:  # noqa: BLE001
        plats = os.environ.get("JAX_PLATFORMS") or ""
    return plats.strip().lower() == "cpu"


def _machine_fingerprint() -> str:
    import hashlib
    import platform
    txt = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    txt += line
                    break
    except OSError:
        pass
    return hashlib.sha1(txt.encode()).hexdigest()[:12]


_CACHE_DIR = os.environ.get("CYLON_TPU_COMPILE_CACHE",
                            os.path.expanduser("~/.cache/cylon_tpu/jax"))
if _cpu_only():
    _CACHE_DIR = ""
COMPILE_CACHE_ENABLED = False
if _CACHE_DIR not in ("", "0"):
    _CACHE_DIR = os.path.join(_CACHE_DIR, _machine_fingerprint())
    try:
        os.makedirs(_CACHE_DIR, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        COMPILE_CACHE_ENABLED = True
    except Exception:  # noqa: BLE001 — read-only fs: run uncached
        pass


def _env_flag(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v not in ("0", "false", "False", "")


#: Print [BENCH] timing lines (reference: CYLON_BENCH_TIMER, util/macros.hpp:102).
BENCH_TIMINGS = _env_flag("CYLON_TPU_BENCH", False)

#: Phase-timing attribution mode (``CYLON_TPU_TIMING``).  ``block``
#: (default): ``timing.maybe_block`` syncs the device inside each region
#: so async work is charged to the phase that dispatched it — exact
#: attribution, but it SERIALIZES piece production against join compute,
#: perturbing exactly the overlap the pipeline exists for.  ``async``:
#: regions record dispatch-only wall time and the caller blocks ONCE at
#: iteration end (bench.py) — phase numbers stop hiding overlap.
TIMING_ASYNC = os.environ.get("CYLON_TPU_TIMING", "block") == "async"

#: Consume range pieces as PACKED windows (relational/piece.PackedPiece):
#: the pipelined join slices + unpacks lanes INSIDE the jitted join
#: program instead of materializing each piece to full-width HBM columns
#: and re-packing.  Off = the seed's materialize-then-join path (kept as
#: the equivalence reference; tests compare the two exactly).
PACKED_PIECES = _env_flag("CYLON_TPU_PACKED_PIECES", True)

#: Phase-overlapped piece scheduling (exec/pipeline.pipelined_join): the
#: setup phases (build sort, range bounds, probe targets, probe sort)
#: dispatch back-to-back with NO host sync between them — their host-side
#: outputs resolve in ONE batched pull at a designated sync point — and
#: per-piece phase work for piece r+1 dispatches while piece r is being
#: consumed (typed faults raised while dispatching ahead are HELD and
#: re-raised at the piece's consume point, so the recovery ladder sees
#: the same consensus-coherent event order with overlap on or off).
#: Off = the prior per-phase-sync dispatch behavior (escape hatch).
PACKED_OVERLAP = _env_flag("CYLON_TPU_PACKED_OVERLAP", True)

#: Donate per-piece scratch (phase-1 carry/payload buffers, splitter
#: operands, the pipeline's dead sorted-table columns at pack time)
#: through the jitted programs via donate_argnums, so the steady-state
#: piece loop reuses buffers instead of re-allocating per piece.  The
#: HBM ledger credits donated bytes against pack admission
#: (exec/memory.ensure_headroom(reuse=)).  Results are bit-equal with
#: donation on or off (tests/test_pipeline.py::TestPackedPieces).
DONATE_BUFFERS = _env_flag("CYLON_TPU_DONATE", True)

#: Route the pipelined join's phase-1 probe (per-row range assignment
#: against the build side's key-group splitters) through the Pallas TPU
#: kernel in ops/pallas_probe.py instead of the XLA (rows x splitters)
#: comparison matrix.  Bit-equal by construction (same lexicographic
#: algebra); interpreter fallback exercises the kernel on CPU rigs.
#: Default off — opt in per run; eligibility (int-kind key operands,
#: tile-aligned capacity) still gates per call site.
PALLAS_PROBE = _env_flag("CYLON_TPU_PALLAS_PROBE", False)

#: AOT pre-compile (lower().compile()) the per-piece join programs for
#: every distinct piece-capacity pair BEFORE the range loop, so a
#: mid-stream capacity change never stalls dispatch on a compile.  The
#: AOT executable lands in the persistent compile cache (the in-process
#: jit call path re-loads it from there), so this only pays off where
#: that cache is enabled — accelerator processes; CPU runs skip it.
PREWARM_PIECE_PROGRAMS = _env_flag("CYLON_TPU_PREWARM", True)

#: Round variable capacities up to powers of two to bound recompilation.
POW2_CAPACITIES = _env_flag("CYLON_TPU_POW2_CAPS", True)

#: Shape-family canonicalization at INGEST (exec/compiler.family_cap):
#: single-controller tables pad their row capacity to the same pow2 family
#: buckets the multi-rank distributor already uses, so N tenants with
#: near-miss row counts share ONE compiled program per plan shape instead
#: of compiling per-tenant.  Padding rides the existing validity lanes —
#: results stay bit- and order-equal (tests/test_compiler.py).  The
#: decision is a pure function of the row count (rank-uniform, no vote).
#: ``CYLON_TPU_SHAPE_FAMILIES=0`` restores exact-shape placement.
SHAPE_FAMILIES = _env_flag("CYLON_TPU_SHAPE_FAMILIES", True)

#: Bounded in-process compile ledger (exec/compiler): maximum LIVE
#: compiled programs per mesh across all program_cache builders; above it
#: the facade retires least-recently-used programs (re-use recompiles,
#: optionally warm from the persistent cache).  0 (default) = unbounded —
#: only the per-builder PROGRAM_CACHE_SIZE LRU applies.  In multiprocess
#: sessions the eviction count rides the count-consensus wire so every
#: rank drops the same programs.
COMPILE_BUDGET = int(os.environ.get("CYLON_TPU_COMPILE_BUDGET", "0"))

#: Facade-owned persistent compile-cache directory (exec/compiler):
#: houses the compile-intent journal, the quarantine ledger and the
#: warm-manifest (and, on accelerator platforms, arms jax's own disk
#: cache under ``<dir>/xla``).  Empty (default) = the facade's durable
#: layer is DISARMED: zero filesystem writes on the happy path.
COMPILE_CACHE_DIR = os.environ.get("CYLON_TPU_COMPILE_CACHE_DIR", "")

#: Compile watchdog deadline in seconds (0 = off, the default): each
#: facade-routed ``.lower()``/``.compile()``/first-trace call runs under
#: this timeout and a hung compile surfaces as a typed
#: CompileTimeoutError instead of wedging the rank (exec/compiler,
#: same worker-thread pattern as the exchange watchdog).
COMPILE_TIMEOUT_S = float(os.environ.get("CYLON_TPU_COMPILE_TIMEOUT_S", "0"))

#: High-cardinality string-key crossover: columns with at least MIN_ROWS
#: rows whose sampled distinct ratio reaches RATIO take the hashed-codes
#: path (core.column.HashedStrings) instead of building a sorted
#: dictionary — dictionary construction (np.unique over every value) is a
#: host-memory wall at ~1e8+ distinct strings.
STRING_HASH_MIN_ROWS = int(os.environ.get("CYLON_TPU_STRING_HASH_MIN",
                                          str(4_000_000)))
STRING_HASH_RATIO = float(os.environ.get("CYLON_TPU_STRING_HASH_RATIO",
                                         "0.5"))

#: Per-factory bound on cached compiled programs (shard_map/jit factories
#: are memoized on static args; long-lived processes joining many distinct
#: schemas would otherwise accumulate executables without limit).  LRU:
#: eviction drops the jit wrapper (and its executables); re-use recompiles.
PROGRAM_CACHE_SIZE = int(os.environ.get("CYLON_TPU_PROGRAM_CACHE", "256"))

#: Per-shard exchange RECEIVE allocation ceiling (bytes, accelerators
#: only): a predicted receive above this fails fast with an OOM-shaped
#: error BEFORE allocating — a real device OOM poisons this rig's
#: process, so preempting a doomed alloc is the only clean failure.  The
#: default leaves headroom under a 16 GB HBM for inputs + exchange
#: staging; the remedy for receive concentration is the heavy-key split.
EXCHANGE_RECV_BUDGET_BYTES = int(os.environ.get(
    "CYLON_TPU_EXCHANGE_RECV_BUDGET", str(12 * 1024**3)))
#: apply the receive guard on CPU meshes too (tests; host RAM is
#: normally far above HBM-sized budgets, so default off)
EXCHANGE_RECV_GUARD_CPU = _env_flag("CYLON_TPU_EXCHANGE_GUARD_CPU", False)

#: HBM budget for the resident-allocation ledger (exec/memory), in TOTAL
#: bytes across the mesh.  0 (default) = platform-detected: per-chip
#: ``bytes_limit`` × device count on accelerators, unlimited on CPU rigs
#: (host RAM, not HBM, is the ceiling there).  Set it below the resident
#: working set to force the host spill tier — cold packed sources evict
#: to host RAM and re-upload per piece window (docs/robustness.md).
HBM_BUDGET_BYTES = int(os.environ.get("CYLON_TPU_HBM_BUDGET", "0"))

#: Host spill tier switch (``CYLON_TPU_SPILL=0`` disables eviction; the
#: ledger keeps accounting either way).  With spill off, memory pressure
#: degrades through the pre-existing rungs only (chunk escalation /
#: typed abort).
SPILL_ENABLED = _env_flag("CYLON_TPU_SPILL", True)

#: Host-side ledger budget for the DISK tier (bytes of host-resident
#: spill pages across the process; 0 = unlimited, disk tier disarmed).
#: When device→host evictions push the host-resident spill balance past
#: this, cold host pages demote to per-rank spill files under
#: ``CYLON_TPU_SPILL_DIR`` — the residency ladder's final rung
#: (docs/robustness.md "Disk tier & scan pushdown").  With it unset the
#: disk tier adds ZERO filesystem writes and zero extra work.
HOST_BUDGET_BYTES = int(os.environ.get("CYLON_TPU_HOST_BUDGET", "0"))

#: Root directory for the disk tier's per-rank spill page files
#: (``<dir>/rank<r>/<owner>.a<j>.s<k>.spill.npy``).  Empty = a private
#: temp directory created lazily on the first demote.  Spill files are
#: PROCESS-TRANSIENT (unlike checkpoints): their hashes live in memory
#: and a fresh process never reads a predecessor's files.
SPILL_DIR = os.environ.get("CYLON_TPU_SPILL_DIR", "")

#: Exchange watchdog deadline in seconds (0 = off, the default): blocking
#: multihost exchange host-syncs run under this timeout and a peer hang
#: surfaces as a typed RankDesyncError (site + last-known phase attached)
#: instead of an infinite block.  See exec/recovery.exchange_watchdog and
#: docs/robustness.md.  Fault injection (CYLON_TPU_FAULTS, same doc) is
#: parsed by exec/recovery directly.
EXCHANGE_WATCHDOG_S = float(os.environ.get("CYLON_TPU_WATCHDOG_S", "0"))

#: A join side at or below this row count is REPLICATED (allgather)
#: instead of shuffling both sides — the broadcast-hash-join cutover.
BROADCAST_JOIN_ROWS = int(os.environ.get("CYLON_TPU_BROADCAST_JOIN_ROWS",
                                         "65536"))

# Heavy-key (skew) split tuning — reference analog: the sampled partition
# machinery of table.cpp:620-689 applied to skew (SURVEY.md §7 hard-part
# 4).  Detection runs on the ROW HASH of the (possibly multi-column) key
# tuple, so float keys and multi-column keys participate uniformly and
# the flag predicate is exactly the shuffle-routing hash.
#: Rows sampled per shard for the heavy-hitter estimate:
SKEW_SAMPLE = int(os.environ.get("CYLON_TPU_SKEW_SAMPLE", "4096"))
#: Minimum per-shard sampled share for a key to enter the estimate:
SKEW_MIN_SHARE = float(os.environ.get("CYLON_TPU_SKEW_MIN_SHARE", "0.01"))
#: A key is heavy when its weighted global share exceeds FACTOR / world
#: (1.0 = one full shard's worth of rows):
SKEW_GLOBAL_FACTOR = float(os.environ.get("CYLON_TPU_SKEW_FACTOR", "1.0"))
#: At most this many heavy keys split per join:
SKEW_MAX_KEYS = int(os.environ.get("CYLON_TPU_SKEW_MAX_KEYS", "8"))
#: Replication guard: skip the split when the BUILD side's heavy rows,
#: replicated world-ways, would exceed GUARD_RATIO x the build size AND
#: GUARD_ROWS rows — W-way replication would recreate the blow-up the
#: split avoids.
SKEW_GUARD_RATIO = float(os.environ.get("CYLON_TPU_SKEW_GUARD_RATIO", "2.0"))
SKEW_GUARD_ROWS = int(os.environ.get("CYLON_TPU_SKEW_GUARD_ROWS", "65536"))

# Adaptive skew-split join (relational/skew.py — the plan facade, lint
# rule TS115; docs/skew.md).  Heavy probe keys detected through the
# weighted Misra-Gries sketch (obs/sketch) are split across a contiguous
# rank group (order-preserving salted sub-partitioning) with the matching
# build rows duplicate-broadcast to the group; the output is stitched
# back bit-equal AND order-equal to the unsplit hash plan.
#: Master switch (default ARMED — "0" falls back to plain hashing for
#: inner/left/right/outer; semi/anti keep the legacy round-robin spread):
SKEW_SPLIT = os.environ.get("CYLON_TPU_SKEW_SPLIT", "1") != "0"
#: Conservative absolute share floor: a key must hold at least this
#: fraction of the probe side (in addition to exceeding
#: SKEW_GLOBAL_FACTOR / world) before the facade will split it — at
#: large worlds 1/W alone is far too eager for the stitch's extra pass:
SKEW_SPLIT_SHARE = float(os.environ.get("CYLON_TPU_SKEW_SPLIT_SHARE",
                                        "0.05"))
#: Fan-out oversubscription: a key with estimated share s splits over
#: ceil(s * world * FANOUT_FACTOR) contiguous ranks (clamped to
#: [2, world] and to the key's exact row count):
SKEW_FANOUT_FACTOR = float(os.environ.get("CYLON_TPU_SKEW_FANOUT_FACTOR",
                                          "1.25"))

# Multi-slice topology tier (cylon_tpu/topo — the plan facade, lint rule
# TS116; docs/topology.md).  SURVEY §5.8: "DCN between pods via jax's
# multi-slice runtime" — inter-slice ≠ intra-slice, so the exchange goes
# hierarchical on a multi-slice fabric: slice-local all-to-all over ICI
# (align rows on the destination's gateway-local rank), then ONE
# aggregated cross-slice exchange over DCN, bit- and order-equal to the
# flat plan by the slice-major layout.
#: Master switch for the hierarchical (two-hop) shuffle route on
#: multi-slice topologies.  "0" keeps the flat one-hop exchange on any
#: topology (the comparison baseline chaos/bench legs run).  Single-slice
#: topologies always take the flat route regardless — zero extra
#: collectives, zero host syncs.
TOPO_SHUFFLE = _env_flag("CYLON_TPU_TOPO_SHUFFLE", True)
#: ``CYLON_TPU_SLICES=<n>`` declares an n-slice two-tier fabric over the
#: visible devices (contiguous slice-major blocks) — the CPU-grid
#: simulation knob tests and chaos schedules use; parsed by
#: cylon_tpu/topo/model.py (real multi-slice TPU fleets are discovered
#: from device attributes instead).

#: Distributed-sort splitter samples per shard: grows with the world size
#: (more shards need finer splitters for the same balance; the reference's
#: SortOptions.num_samples is likewise caller-tunable, table.hpp:358).
SORT_SAMPLES_PER_SHARD = int(os.environ.get("CYLON_TPU_SORT_SAMPLES", "0"))


def sort_samples(world: int) -> int:
    """Splitter samples per shard: explicit override, else 64 minimum
    scaled linearly with the world (16 x W) so splitter resolution keeps
    pace with the number of cut points."""
    if SORT_SAMPLES_PER_SHARD > 0:
        return SORT_SAMPLES_PER_SHARD
    return max(64, 16 * world)


#: Defer inner-join output materialization so a same-key groupby can consume
#: the pre-expansion sorted state (relational/fused.py); any other access
#: materializes transparently.  Reference analog: the streaming ops DAG
#: (cpp/src/cylon/ops/, SURVEY §2 C9).
DEFER_JOIN = _env_flag("CYLON_TPU_DEFER_JOIN", True)

#: route large dense grouped-reduce gathers through the Pallas windowed
#: kernel (ops/pallas_gather) on TPU — ~6x the XLA matrix gather at bench
#: density; span overflows auto-redispatch the plain program
WINDOWED_GATHER = _env_flag("CYLON_TPU_WINDOWED_GATHER", True)


def pow2ceil(n: int) -> int:
    """Bucket a dynamic capacity to the next 2^(b-5) step for n in
    (2^(b-1), 2^b] (exact powers of two below 16Ki): 16 steps per octave,
    worst-case overshoot 2^(b-5)/2^(b-1) = 6.25%.  Keeps the family of
    compiled shapes logarithmic while bounding overshoot — at tens of
    millions of rows every output-space gather/scatter pays for overshoot
    (~15 ns/row measured), which dwarfs the marginal compiles (and
    capacity hysteresis amortizes those anyway)."""
    n = max(int(n), 1)
    if not POW2_CAPACITIES:
        return n
    if n <= 16384:
        return 1 << (n - 1).bit_length()
    step = 1 << ((n - 1).bit_length() - 5)
    return -(-n // step) * step
