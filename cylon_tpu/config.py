"""Global configuration for cylon_tpu.

The reference framework is int64-first (Arrow/pandas default integer keys,
BASELINE.json's 1B int64-key join).  JAX defaults to 32-bit; we enable x64 at
import so device tables can faithfully hold pandas/Arrow int64/float64 columns.
Set ``CYLON_TPU_X64=0`` to opt out (columns will then be downcast on transfer).

Reference analog: the CMake/feature-flag + env-var config surface
(cpp/CMakeLists.txt:129-441, redis_ucx_ucc_oob_context.cpp:104-105) collapses
into this module plus per-op option dataclasses.
"""

from __future__ import annotations

import os

import jax

X64_ENABLED = os.environ.get("CYLON_TPU_X64", "1") != "0"
if X64_ENABLED:
    jax.config.update("jax_enable_x64", True)

# Persistent compiled-program cache: TPC-H-class workloads compile dozens
# of distinct programs and remote TPU compiles cost seconds-to-minutes
# each; the persistent cache makes every rerun warm (verified working over
# the axon remote-compile tunnel).  Opt out with CYLON_TPU_COMPILE_CACHE=0.
_CACHE_DIR = os.environ.get("CYLON_TPU_COMPILE_CACHE",
                            os.path.expanduser("~/.cache/cylon_tpu/jax"))
if _CACHE_DIR not in ("", "0"):
    try:
        os.makedirs(_CACHE_DIR, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 — read-only fs: run uncached
        pass


def _env_flag(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v not in ("0", "false", "False", "")


#: Print [BENCH] timing lines (reference: CYLON_BENCH_TIMER, util/macros.hpp:102).
BENCH_TIMINGS = _env_flag("CYLON_TPU_BENCH", False)

#: Round variable capacities up to powers of two to bound recompilation.
POW2_CAPACITIES = _env_flag("CYLON_TPU_POW2_CAPS", True)

#: High-cardinality string-key crossover: columns with at least MIN_ROWS
#: rows whose sampled distinct ratio reaches RATIO take the hashed-codes
#: path (core.column.HashedStrings) instead of building a sorted
#: dictionary — dictionary construction (np.unique over every value) is a
#: host-memory wall at ~1e8+ distinct strings.
STRING_HASH_MIN_ROWS = int(os.environ.get("CYLON_TPU_STRING_HASH_MIN",
                                          str(4_000_000)))
STRING_HASH_RATIO = float(os.environ.get("CYLON_TPU_STRING_HASH_RATIO",
                                         "0.5"))

#: Per-factory bound on cached compiled programs (shard_map/jit factories
#: are memoized on static args; long-lived processes joining many distinct
#: schemas would otherwise accumulate executables without limit).  LRU:
#: eviction drops the jit wrapper (and its executables); re-use recompiles.
PROGRAM_CACHE_SIZE = int(os.environ.get("CYLON_TPU_PROGRAM_CACHE", "256"))

#: Defer inner-join output materialization so a same-key groupby can consume
#: the pre-expansion sorted state (relational/fused.py); any other access
#: materializes transparently.  Reference analog: the streaming ops DAG
#: (cpp/src/cylon/ops/, SURVEY §2 C9).
DEFER_JOIN = _env_flag("CYLON_TPU_DEFER_JOIN", True)


def pow2ceil(n: int) -> int:
    """Bucket a dynamic capacity to the next 2^(b-5) step for n in
    (2^(b-1), 2^b] (exact powers of two below 16Ki): 16 steps per octave,
    worst-case overshoot 2^(b-5)/2^(b-1) = 6.25%.  Keeps the family of
    compiled shapes logarithmic while bounding overshoot — at tens of
    millions of rows every output-space gather/scatter pays for overshoot
    (~15 ns/row measured), which dwarfs the marginal compiles (and
    capacity hysteresis amortizes those anyway)."""
    n = max(int(n), 1)
    if not POW2_CAPACITIES:
        return n
    if n <= 16384:
        return 1 << (n - 1).bit_length()
    step = 1 << ((n - 1).bit_length() - 5)
    return -(-n // step) * step
