"""Table-level groupby-aggregate: local + distributed.

TPU-native equivalent of the reference's groupby engines: the two-phase
``DistributedHashGroupBy`` (groupby/groupby.cpp:33 — associative ops combine
locally, shuffle the much smaller per-group intermediates, combine again;
non-associative ops shuffle raw rows first) and the MapReduce engine's
six-stage flow (mapreduce/mapreduce.hpp:56-76).  Group identity is a dense
rank (ops/pack.py) instead of a hash map; aggregations are XLA segment
reductions (ops/groupby.py).

The intermediate "table" between phases reuses the ordinary shuffle engine —
intermediates are just columns keyed by the group keys, exactly how the
reference ships ``MapReduceKernel`` intermediates through ArrowAllToAll.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .. import config
from ..utils.cache import jit, program_cache
from ..core.column import Column
from ..core.dtypes import LogicalType, from_numpy_dtype, physical_np_dtype
from ..core.table import Table
from ..ctx.context import ROW_AXIS
from ..ops import groupby as gbk
from ..ops import pack
from ..status import InvalidError
from ..utils import timing
from ..utils.host import host_array
from .common import (PAD_L, REP, ROW, BoundedCache, col_arrays,
                     live_mask, narrow32_flags)
from .repart import shuffle_table

shard_map = jax.shard_map

_VALID_OPS = gbk.ASSOCIATIVE | gbk.NON_ASSOCIATIVE

#: callsite-signature -> last observed group-count bucket
_SEG_CACHE = BoundedCache()

#: optimistic first-dispatch segment space for large-cap groupbys with no
#: hysteresis prediction yet: small enough that the dense one-hot regime
#: stays at its ~9 ns/row flat cost — scatter-heavy
#: programs at multi-10M shapes have pathological XLA:TPU compile times
#: (observed 50+ min), while the dense form compiles in seconds.  A
#: mispredict (more groups than this) is detected via the returned
#: n_groups and re-dispatched at the true bucket (see the dispatch
#: comment in _groupby_aggregate_impl).
_FIRST_SEG_CAP = 512

#: program-signature -> first ladder attempt index that compiled (see
#: :func:`_pad_ladder`)
_PAD_CACHE = BoundedCache()


def _is_compiler_crash(e: Exception) -> bool:
    """True when the XLA compiler process died rather than the program
    being invalid — delegates to the per-process probe-compiled
    signature set (:func:`cylon_tpu.exec.recovery.is_compiler_crash`,
    primed at first env creation, ``CYLON_TPU_CRASH_SIGS`` overrides),
    so the pad ladder engages on whatever surfacing shape THIS platform
    produces instead of a substring list frozen at authoring time."""
    from ..exec.recovery import is_compiler_crash
    return is_compiler_crash(e)


def _pad_ladder(sig_key, attempts):
    """Run the first ``attempts`` entry that compiles.  Each entry is a
    ``(tag, thunk)``; on an XLA:TPU compiler crash (a compile-time SIGSEGV,
    not a data error) the next variant is tried — dummy gather lanes shift
    the crashing width, the final entry is the scatter fallback.  The
    winning index is remembered per program signature so steady state
    dispatches straight to a compiling variant."""
    start = min(_PAD_CACHE.get(sig_key, 0), len(attempts) - 1)
    last = None
    for idx in range(start, len(attempts)):
        try:
            res = attempts[idx][1]()
            if idx != start:
                _PAD_CACHE.put(sig_key, idx)
            return res
        except Exception as e:  # noqa: BLE001
            if idx + 1 < len(attempts) and _is_compiler_crash(e):
                from ..utils.logging import log
                log.warning(
                    "TPU compiler crash on groupby variant %r; retrying "
                    "with %r: %.300s", attempts[idx][0],
                    attempts[idx + 1][0], e)
                last = e
                continue
            raise
    raise last

#: static intermediate-column order per op (mapreduce.hpp:27 analog: MEAN ->
#: {sum,count}, VAR/STD -> {sum,sumsq,count})
INTER_NAMES = {
    "sum": ("sum",),
    "sumsq": ("sumsq",),
    "count": ("count",),
    "min": ("count", "min"),
    "max": ("count", "max"),
    "mean": ("count", "sum"),
    "var": ("count", "sum", "sumsq"),
    "std": ("count", "sum", "sumsq"),
}


def _normalize_aggs(aggs):
    """aggs: list of (value_col, op) or (value_col, op, q). Returns list of
    (col, op, q, out_name)."""
    out, seen = [], set()
    for a in aggs:
        col, op = a[0], a[1]
        q = a[2] if len(a) > 2 else 0.5
        if op == "median":
            op, q = "quantile", 0.5
        if op not in _VALID_OPS:
            raise InvalidError(f"unknown aggregation {op!r}")
        name = f"{col}_{a[1]}"
        if op == "quantile" and a[1] == "quantile" and len(a) > 2:
            name = f"{col}_quantile_{q:g}"
        if name in seen:
            raise InvalidError(f"duplicate aggregation output {name!r}")
        seen.add(name)
        out.append((col, op, float(q), name))
    return out


def _group_keys(by_datas, by_valids, vc, grouped: bool = False,
                narrow: tuple | None = None):
    """Per-shard dense group ids; padding rows route to trash segment ``cap``
    and never contribute a group (live rows sort first, so live ranks are a
    dense prefix 0..n_groups-1).

    ``grouped=True`` (table carries ``grouped_by`` metadata — join/sort
    output): equal keys are already contiguous, so ids come from boundary
    flags + prefix sum instead of a rank sort.  ``narrow`` = static
    narrow32 flags for the sort-operand packing (see common.narrow32_flags).
    """
    cap = by_datas[0].shape[0]
    mask = live_mask(vc, cap)
    if grouped:
        gids, n_groups, first = pack.grouped_gids(list(by_datas),
                                                  list(by_valids), mask,
                                                  narrow)
        return gids, n_groups, mask, first
    ko = pack.key_operands(list(by_datas), list(by_valids), row_mask=mask,
                           pad_key=PAD_L, narrow32=narrow)
    gids, _ = pack.dense_rank(ko)
    n_groups = jnp.max(jnp.where(mask, gids, -1)) + 1
    gids = jnp.where(mask, gids, cap)
    return gids, n_groups.astype(jnp.int32), mask, None


def _value_mask(mask, val, valid):
    """Row mask for aggregation payloads: live row AND valid AND (for float
    payloads) not-NaN — pandas skipna=True semantics (NaN is stored as a
    float payload with validity=None, so validity alone misses it)."""
    vmask = mask if valid is None else (mask & valid)
    if jnp.issubdtype(val.dtype, jnp.floating):
        vmask = vmask & ~jnp.isnan(val)
    return vmask


def _plan_vspec(val_cols, by_cols, narrow, n_inters: int = 1):
    """Sort-path eligibility: a LaneSpec over (value cols ++ key cols) when
    the measured cost model favors riding the rank sort over per-op segment
    scatters.  Laneable columns cost ~1.7 ns/row/lane as sort payload; f64
    columns (laneless — any f64 bitcast/sort-payload SIGSEGVs the XLA:TPU
    compiler, measured v5e libtpu 2026-07) ride via ONE u32 row-index
    payload lane + one batched (n, K) f64 side-matrix gather at the sort
    permutation (matrix gathers amortize: ~15.5·(1+0.2·(K-1)) ns/row
    total).  The fallback costs ~12 ns/row per scatter-reduced intermediate
    (``n_inters``) plus the dense-rank gid scatter-back — and degrades
    further at tiny group counts where scatter-adds serialize on
    collisions, so ties go to the sort path."""
    from ..ops import lanes
    cand = lanes.plan_lanes(
        tuple(str(c.data.dtype) for c in val_cols + by_cols),
        tuple(c.validity is not None for c in val_cols + by_cols),
        narrow32_flags(val_cols) + narrow)
    n_side = sum(1 for c in cand.cols if not c.lanes)
    sort_ns = 1.7 * (cand.n_lanes + (1 if n_side else 0))
    if n_side:
        sort_ns += 15.5 * (1 + 0.2 * (n_side - 1))
    scatter_ns = 12.0 * max(n_inters, 1) + 8.8
    return cand if sort_ns <= scatter_ns else None


def _rep_keys(by_datas, by_valids, gids, seg_cap):
    """Representative key row per group (first source index)."""
    rep = gbk.group_first_index(gids, seg_cap)
    safe = jnp.clip(rep, 0, by_datas[0].shape[0] - 1)
    key_out = tuple(d[safe] for d in by_datas)
    kval_out = tuple(v[safe] if v is not None else None for v in by_valids)
    return key_out, kval_out


def _sort_state(vc, by_datas, by_valids, val_datas, val_valids, narrow,
                vspec):
    """THE SORT PATH (non-grouped input): key operands + value/key u32
    payload lanes through one ``lax.sort`` — the input becomes
    run-contiguous, so downstream reductions use the grouped machinery.
    Returns (gids, n_groups, mask, first, by_datas, by_valids, val_datas,
    val_valids) with the column arrays replaced by their sorted versions.
    Padding rows sort last (pad-key operand), so the live prefix is exactly
    the first vc[rank] positions."""
    from ..ops import lanes
    cap = by_datas[0].shape[0]
    my = jax.lax.axis_index(ROW_AXIS)
    n_live = vc[my].astype(jnp.int32)
    mask0 = live_mask(vc, cap)
    ko = pack.key_operands(list(by_datas), list(by_valids), row_mask=mask0,
                           pad_key=PAD_L, narrow32=narrow)
    all_datas = list(val_datas) + list(by_datas)
    # n_lanes == 0 (every column laneless f64, none nullable): nothing to
    # pack — the index lane alone carries the permutation
    vmat = (lanes.pack_lanes(vspec, all_datas,
                             list(val_valids) + list(by_valids))
            if vspec.n_lanes else None)
    # laneless (f64) columns cannot ride the sort — any f64 bitcast or sort
    # payload SIGSEGVs the XLA:TPU compiler — so a u32 row-index payload
    # lane rides instead and ONE (cap, K) f64 matrix gather at the sorted
    # permutation moves all of them after the sort (batched: ~6 ns/row/col
    # at K=5 vs ~16 ns/row/col for separate 1-D gathers, measured v5e)
    laneless = tuple(i for i, c in enumerate(vspec.cols) if not c.lanes)
    extra = ((jnp.arange(cap, dtype=jnp.uint32),) if laneless else ())
    nk = len(ko.ops)
    nl = vspec.n_lanes
    lane_ops = tuple(vmat[:, j] for j in range(nl)) if vmat is not None else ()
    sorted_all = jax.lax.sort(ko.ops + lane_ops + extra,
                              num_keys=nk, is_stable=False)
    pos = jnp.arange(cap, dtype=jnp.int32)
    mask = pos < n_live
    first = (pack.neighbor_flags(sorted_all[:nk], ko.kinds)
             .astype(bool) | (pos == 0)) & mask
    gid = jnp.cumsum(first.astype(jnp.int32)).astype(jnp.int32) - 1
    n_groups = (jnp.max(jnp.where(mask, gid, -1)) + 1).astype(jnp.int32)
    gids = jnp.where(mask, gid, cap)
    if nl:
        smat = jnp.stack(sorted_all[nk:nk + nl], axis=1)
        sdatas, svalids = lanes.unpack_lanes(vspec, smat)
        sdatas, svalids = list(sdatas), list(svalids)
    else:
        sdatas = [None] * len(vspec.cols)
        svalids = [None] * len(vspec.cols)
    if laneless:
        perm = sorted_all[-1].astype(jnp.int32)
        fmat = jnp.stack([all_datas[i] for i in laneless], axis=1)
        fsorted = fmat[perm]
        for j, i in enumerate(laneless):
            sdatas[i] = fsorted[:, j]
    nv = len(val_datas)
    return (gids, n_groups, mask, first, tuple(sdatas[nv:]),
            tuple(svalids[nv:]), tuple(sdatas[:nv]), tuple(svalids[:nv]))


def _runs_reduce(specs_ops, val_datas, vmasks, gids, first, mask, vc,
                 seg_cap, by_datas, by_valids, narrow, vnarrow,
                 pad_lanes: int = 0, gather_parts: int = 1):
    """Per-op intermediate dicts + representative keys for run-contiguous
    (grouped or freshly sorted) input: every cumsum-able intermediate AND
    the min/max ops' counts ride grouped_reduce's single prefix-diff
    gather; only the min/max extrema themselves need segment scatters.
    Ops outside CUMSUMMABLE/min/max get no intermediate entry (callers'
    non-associative branches compute their own)."""
    my = jax.lax.axis_index(ROW_AXIS)
    n_live = vc[my].astype(jnp.int32)
    starts = gbk.grouped_starts(gids, first, mask, n_live, seg_cap)
    batch = []      # (batched op name, spec index)
    for i, op in enumerate(specs_ops):
        if op in gbk.CUMSUMMABLE:
            batch.append((op, i))
        elif op in ("min", "max"):
            batch.append(("count", i))
    inters_b, key_out, kval_out, _wok = gbk.grouped_reduce(
        [b[0] for b in batch], [val_datas[b[1]] for b in batch],
        [vmasks[b[1]] for b in batch], starts, n_live,
        list(by_datas), list(by_valids), seg_cap, key_narrow=narrow,
        value_narrow=[(bool(vnarrow[b[1]]) if vnarrow else False)
                      for b in batch], pad_lanes=pad_lanes,
        gather_parts=gather_parts)
    inters: dict = {}
    for (op, i), d in zip(batch, inters_b):
        inters.setdefault(i, {}).update(d)
    for i, op in enumerate(specs_ops):
        if op == "min":
            inters[i]["min"] = gbk.seg_min(val_datas[i], gids, seg_cap,
                                           vmasks[i])
        elif op == "max":
            inters[i]["max"] = gbk.seg_max(val_datas[i], gids, seg_cap,
                                           vmasks[i])
    return inters, key_out, kval_out


@program_cache()
def _combine_fn(mesh: Mesh, ops: tuple, seg_cap: int, grouped: bool,
                narrow: tuple, vspec=None, val_map: tuple = (),
                pad_lanes: int = 0, gather_parts: int = 1):
    """Phase 1 per shard: group keys, reduce each (col, op) into
    intermediate arrays of static length seg_cap (rank-ordered dense
    prefix), gather per-group key representatives.  With ``vspec`` the
    value/key columns ride the rank sort (see :func:`_sort_state`) and the
    intermediates come from the run-contiguous prefix-diff machinery
    instead of per-op segment scatters.  Sum intermediates are never
    narrowed here — phase 2 sums them AGAIN across shards, so the
    single-shard rows·max|v| < 2^31 proof does not cover them."""

    def per_shard(vc, by_datas, by_valids, uval_datas, uval_valids):
        if vspec is not None and not grouped:
            (gids, n_groups, mask, first, by_datas, by_valids, uval_datas,
             uval_valids) = _sort_state(vc, by_datas, by_valids, uval_datas,
                                        uval_valids, narrow, vspec)
        else:
            gids, n_groups, mask, first = _group_keys(by_datas, by_valids,
                                                      vc, grouped, narrow)
        val_datas = tuple(uval_datas[j] for j in val_map)
        val_valids = tuple(uval_valids[j] for j in val_map)
        vmasks = [_value_mask(mask, val_datas[i], val_valids[i])
                  for i in range(len(ops))]
        if first is not None:
            inters, key_out, kval_out = _runs_reduce(
                ops, val_datas, vmasks, gids, first, mask, vc, seg_cap,
                by_datas, by_valids, narrow, (), pad_lanes, gather_parts)
            inter_out = [tuple(inters[i][k] for k in INTER_NAMES[op])
                         for i, op in enumerate(ops)]
        else:
            key_out, kval_out = _rep_keys(by_datas, by_valids, gids, seg_cap)
            inter_out = []
            for i, op in enumerate(ops):
                inter = gbk.combine_locally(op, val_datas[i], gids, seg_cap,
                                            vmasks[i])
                inter_out.append(tuple(inter[k] for k in INTER_NAMES[op]))
        return key_out, kval_out, tuple(inter_out), n_groups.reshape(1)

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, ROW, ROW, ROW, ROW),
                             out_specs=(ROW, ROW, ROW, ROW)))


@program_cache()
def _final_fn(mesh: Mesh, ops: tuple, seg_cap: int, ddof: int, narrow: tuple,
              pad_lanes: int = 0, use_runs: bool = True,
              gather_parts: int = 1):
    """Phase 2 per shard: reduce shuffled intermediates under the new key
    grouping, finalize each op.

    Rides THE SORT PATH: instead of dense-ranking the keys (sort + gid
    scatter-back) and per-intermediate segment scatters (~12 ns/row each,
    worse under collision), the intermediates ride the one rank sort as u32
    lanes (f64 sums via the index-lane side gather, see :func:`_sort_state`)
    and every sum-like intermediate (sum/sumsq/count — reduced by summing)
    comes out of the batched prefix-diff gather; only min/max extrema need
    segment scatters.  The reference's phase-2 is ``ReduceShuffledResults``
    (mapreduce/mapreduce.hpp:56-76)."""
    from ..ops import lanes

    def per_shard_scatter(vc, by_datas, by_valids, inter_by_op):
        """Fallback (compiler-crash ladder): dense-rank + per-op segment
        scatters — the pre-sort-path phase 2."""
        gids, n_groups, mask, _ = _group_keys(by_datas, by_valids, vc,
                                              narrow=narrow)
        key_out, kval_out = _rep_keys(by_datas, by_valids, gids, seg_cap)
        res_d, res_v = [], []
        for i, op in enumerate(ops):
            inter = dict(zip(INTER_NAMES[op], inter_by_op[i]))
            red = gbk.reduce_intermediates(inter, gids, seg_cap, mask)
            d, v = gbk.finalize(op, red, ddof)
            res_d.append(d)
            res_v.append(v)
        return key_out, kval_out, tuple(res_d), tuple(res_v), n_groups.reshape(1)

    def per_shard(vc, by_datas, by_valids, inter_by_op):
        if not use_runs:
            return per_shard_scatter(vc, by_datas, by_valids, inter_by_op)
        flat_arrs, flat_kinds = [], []   # kind: 'sum' | 'min' | 'max'
        for i, op in enumerate(ops):
            for nm, arr in zip(INTER_NAMES[op], inter_by_op[i]):
                flat_arrs.append(arr)
                flat_kinds.append("sum" if nm in ("sum", "sumsq", "count")
                                  else nm)
        vspec = lanes.plan_lanes(
            tuple(str(a.dtype) for a in flat_arrs)
            + tuple(str(d.dtype) for d in by_datas),
            (False,) * len(flat_arrs)
            + tuple(v is not None for v in by_valids),
            (False,) * len(flat_arrs) + narrow)
        (gids, n_groups, mask, first, s_by, s_byv, s_arrs, _) = _sort_state(
            vc, by_datas, by_valids, tuple(flat_arrs),
            (None,) * len(flat_arrs), narrow, vspec)
        my = jax.lax.axis_index(ROW_AXIS)
        n_live = vc[my].astype(jnp.int32)
        starts = gbk.grouped_starts(gids, first, mask, n_live, seg_cap)
        sum_idx = [j for j, k in enumerate(flat_kinds) if k == "sum"]
        inters_b, key_out, kval_out, _wok = gbk.grouped_reduce(
            ["sum"] * len(sum_idx), [s_arrs[j] for j in sum_idx],
            [mask] * len(sum_idx), starts, n_live, list(s_by), list(s_byv),
            seg_cap, key_narrow=narrow, pad_lanes=pad_lanes,
            gather_parts=gather_parts)
        red_flat = [None] * len(flat_arrs)
        for j, d in zip(sum_idx, inters_b):
            red_flat[j] = d["sum"]
        for j, k in enumerate(flat_kinds):
            if k == "min":
                red_flat[j] = gbk.seg_min(s_arrs[j], gids, seg_cap, mask)
            elif k == "max":
                red_flat[j] = gbk.seg_max(s_arrs[j], gids, seg_cap, mask)
        res_d, res_v = [], []
        k = 0
        for i, op in enumerate(ops):
            inter = {}
            for nm in INTER_NAMES[op]:
                inter[nm] = red_flat[k]
                k += 1
            if "count" in inter:
                inter["count"] = inter["count"].astype(gbk._int_dtype())
            d, v = gbk.finalize(op, inter, ddof)
            res_d.append(d)
            res_v.append(v)
        return key_out, kval_out, tuple(res_d), tuple(res_v), n_groups.reshape(1)

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, ROW, ROW, ROW),
                             out_specs=(ROW, ROW, ROW, ROW, ROW)))


@program_cache()
def _raw_fn(mesh: Mesh, specs: tuple, seg_cap: int, ddof: int, grouped: bool,
            narrow: tuple, vnarrow: tuple = (), vspec=None,
            val_map: tuple = (), pad_lanes: int = 0, use_runs: bool = True,
            gather_parts: int = 1):
    """Single-phase per shard over raw (already co-located) rows — used for
    non-associative ops, the local path, and the grouped-input fast path
    (join/sort output: no shuffle, no rank sort).  ``vnarrow``: host-proven
    boolean per value column (rows·max|v| fits int32 — derived from
    ``Column.bounds``, reduced to a bool so this cache keys on the
    decision, not on per-batch data bounds), letting the grouped path
    narrow integer sum-prefix lanes.

    ``vspec`` (non-grouped inputs only): a :class:`~.lanes.LaneSpec` over
    (value columns per spec ++ key columns) — the SORT PATH
    (:func:`_sort_state`).  Instead of dense-ranking keys (sort + gid
    scatter-back) and then scatter-reducing every aggregation in source
    order (~12 ns/row per op, measured), the value and key columns ride
    THE rank sort as u32 payload lanes (~1.7 ns/row/lane) and the input
    becomes grouped — every cumsum-able aggregation, the min/max counts
    and the representative keys then come from the run machinery's single
    prefix-diff gather (:func:`_runs_reduce`).  The reference's pipeline
    groupby (groupby/pipeline_groupby.cpp) is the moral analog: sort once,
    reduce runs."""

    def per_shard(vc, by_datas, by_valids, uval_datas, uval_valids):
        # uval_*: one array per DISTINCT value column; val_map expands to
        # per-spec lists (repeated aggs over one column share lanes/sorts)
        if vspec is not None and not grouped:
            (gids, n_groups, mask, first, by_datas, by_valids, uval_datas,
             uval_valids) = _sort_state(vc, by_datas, by_valids, uval_datas,
                                        uval_valids, narrow, vspec)
        else:
            gids, n_groups, mask, first = _group_keys(
                by_datas, by_valids, vc, grouped, narrow)
        val_datas = tuple(uval_datas[j] for j in val_map)
        val_valids = tuple(uval_valids[j] for j in val_map)
        vmasks = [_value_mask(mask, val_datas[i], val_valids[i])
                  for i in range(len(specs))]
        # grouped/sorted fast path: ONE batched prefix-diff pass computes
        # every cumsum-able aggregation, min/max counts AND the
        # representative keys
        batched: dict[int, dict] = {}
        if first is not None and use_runs:
            batched, key_out, kval_out = _runs_reduce(
                tuple(op for op, _ in specs), val_datas, vmasks, gids,
                first, mask, vc, seg_cap, by_datas, by_valids, narrow,
                vnarrow, pad_lanes, gather_parts)
        else:
            key_out, kval_out = _rep_keys(by_datas, by_valids, gids, seg_cap)
        res_d, res_v = [], []
        for i, (op, q) in enumerate(specs):
            vmask = vmasks[i]
            if op in gbk.ASSOCIATIVE:
                if i in batched:
                    inter = batched[i]
                else:
                    inter = gbk.combine_locally(op, val_datas[i], gids,
                                                seg_cap, vmask)
                d, v = gbk.finalize(op, inter, ddof)
            elif op == "nunique":
                ko = pack.key_operands([val_datas[i]], [val_valids[i]])
                d = gbk.nunique(ko, gids, seg_cap, vmask)
                v = None
            else:  # quantile
                d, v = gbk.quantile(val_datas[i], gids, seg_cap, q, vmask)
            res_d.append(d)
            res_v.append(v)
        return key_out, kval_out, tuple(res_d), tuple(res_v), n_groups.reshape(1)

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, ROW, ROW, ROW, ROW),
                             out_specs=(ROW, ROW, ROW, ROW, ROW)))


@program_cache()
def _shrink_fn(mesh: Mesh, new_cap: int):
    def per_shard(d):
        return d[:new_cap]

    return jit(shard_map(per_shard, mesh=mesh, in_specs=ROW,
                             out_specs=ROW))


def _shrink(table: Table, n_rows: np.ndarray) -> Table:
    """Slice each shard's dense row prefix down to a pow2 cap (cuts the cost
    of downstream shuffles/sorts on oversized intermediate tables)."""
    cap = table.capacity
    new_cap = config.pow2ceil(int(n_rows.max()) if n_rows.size else 1)
    if new_cap >= cap:
        return table
    fn = _shrink_fn(table.env.mesh, new_cap)
    cols = {}
    for n, c in table.columns.items():
        d = fn(c.data)
        v = fn(c.validity) if c.validity is not None else None
        cols[n] = Column(d, c.type, v, c.dictionary)
    return Table(cols, table.env, n_rows)


def _result_types(specs, val_cols):
    """Logical type + dictionary of each aggregation result column."""
    types, dicts = [], []
    for (c, op, _, _), col in zip(specs, val_cols):
        if op in ("count", "nunique"):
            types.append(LogicalType.INT64)
            dicts.append(None)
        elif col.type == LogicalType.STRING:  # min/max of strings = codes
            types.append(LogicalType.STRING)
            dicts.append(col.dictionary)
        else:
            src = physical_np_dtype(col.type)
            types.append(from_numpy_dtype(gbk.np_result_dtype(op, src)))
            dicts.append(None)
    return types, dicts


def _result_table(env, by_names, by_cols, key_out, kval_out, res_names,
                  res_d, res_v, res_types, res_dicts, n_groups) -> Table:
    cols = {}
    for n, c, d, v in zip(by_names, by_cols, key_out, kval_out):
        cols[n] = Column(d, c.type, v, c.dictionary)
    for n, d, v, t, dc in zip(res_names, res_d, res_v, res_types, res_dicts):
        phys = physical_np_dtype(t)
        if d.dtype != phys:  # f64 accumulators -> declared result dtype
            d = d.astype(phys)
        # armed-audit overflow guard (result names are `{col}_{op}` — a
        # public contract, so the op suffix is derivable here at the one
        # host assembly point every groupby route funnels through)
        gbk.guard_saturation(n.rsplit("_", 1)[-1], d, column=n)
        cols[n] = Column(d, t, v, dc)
    return Table(cols, env, np.asarray(n_groups, np.int64))


@program_cache()
def _sink_finalize_fn(mesh: Mesh, ops: tuple, ddof: int):
    """Per-shard finalize of a sink combine's DERIVED ops (mean/var/std)
    over the summed (count, sum[, sumsq]) intermediate columns — the
    IDENTICAL :func:`cylon_tpu.ops.groupby.finalize` expressions,
    compiled by the same backend in one program, so FMA-contraction
    decisions match the batch groupby's in-jit finalize and the
    streaming bit-equality contract extends to var/std (an eager
    host-side ``sumsq/c - mean·mean`` computes the multiply and
    subtract as separate dispatches, which XLA would have contracted —
    a 1-ulp fork measured on the CPU rig)."""

    def per_shard(*arrs):
        outs = []
        i = 0
        for op in ops:
            inter = {"count": arrs[i], "sum": arrs[i + 1]}
            i += 2
            if op != "mean":
                inter["sumsq"] = arrs[i]
                i += 1
            d, v = gbk.finalize(op, inter, ddof)
            outs.append(d)
            outs.append(v)
        return tuple(outs)

    n_in = sum(2 if op == "mean" else 3 for op in ops)
    return jit(shard_map(per_shard, mesh=mesh, in_specs=(ROW,) * n_in,
                             out_specs=(ROW,) * (2 * len(ops))))


def combine_sink_partials(partial: Table, by, aggs, chunk_aggs,
                          combine_ops, ddof: int = 1,
                          disjoint: bool = False) -> Table:
    """The sink snapshot/absorb API's COMBINE step: fold a table of
    per-chunk partial aggregates (one row per (chunk, group) — the
    concatenation of a :class:`~cylon_tpu.exec.pipeline.GroupBySink`'s
    adopted partials) into the final public aggregate table, without
    touching the partials themselves — so a streaming view's
    ``read()`` can snapshot a LIVE sink repeatedly while ingestion
    continues (:mod:`cylon_tpu.stream.view`).

    ``chunk_aggs``: the sorted distinct (col, intermediate-op) pairs the
    sink maintains; ``combine_ops``: intermediate-op → combining op
    (sum/min/max); ``disjoint``: the partials' key sets are pairwise
    disjoint (range-partitioned pipelines), so the cross-chunk combine
    groupby is skipped and the partials ARE the final groups.

    Derived ops (mean/var/std) finalize ON DEVICE through the very
    :func:`cylon_tpu.ops.groupby.finalize` the monolithic groupby jits
    (:func:`_sink_finalize_fn`), so whenever the partial sums are EXACT
    (integer payloads, or integer-valued f64 below 2^53 — the
    fixed-point money representation) the combined result is bit-equal
    to a from-scratch batch groupby over all rows, var/std included
    (docs/streaming.md "exactness contract")."""
    env = partial.env
    if disjoint:
        comb = partial

        def part_name(col, i):
            return f"{col}_{i}"
    else:
        combine = [(f"{c}_{i}", combine_ops[i]) for c, i in chunk_aggs]
        comb = groupby_aggregate(partial, by, combine)

        def part_name(col, i):
            return f"{col}_{i}_{combine_ops[i]}"
    # derived ops: one shared device finalize over the summed
    # intermediates (count/sum[/sumsq] per derived column)
    derived = [(col, op) for col, op, *_ in aggs
               if op in ("mean", "var", "std")]
    dev_out: dict[tuple, tuple] = {}
    if derived:
        arrs = []
        for col, op in derived:
            arrs.append(comb.column(part_name(col, "count")).data)
            arrs.append(comb.column(part_name(col, "sum")).data)
            if op != "mean":
                arrs.append(comb.column(part_name(col, "sumsq")).data)
        outs = _sink_finalize_fn(env.mesh, tuple(op for _, op in derived),
                                 int(ddof))(*arrs)
        for j, key in enumerate(derived):
            dev_out[key] = (outs[2 * j], outs[2 * j + 1])
    cols = {}
    for n in by:
        cols[n] = comb.column(n)
    for col, op, *_ in aggs:
        name = f"{col}_{op}"
        if (col, op) in dev_out:
            d, v = dev_out[(col, op)]
            cols[name] = Column(d, from_numpy_dtype(np.dtype(d.dtype)), v)
        else:
            # non-derived ops (sum/count/min/max) ARE their own single
            # intermediate — the combined column passes through renamed
            c = comb.column(part_name(col, op))
            # armed-audit overflow guard at the COMBINE boundary: two
            # partials each below the rail can wrap when folded, and the
            # disjoint pass-through never reaches _result_table's guard
            gbk.guard_saturation(op, c.data, column=name,
                                 site="groupby.combine")
            cols[name] = c
    out = Table(cols, env, np.asarray(comb.valid_counts, np.int64))
    out.grouped_by = None  # combine order is chunk-partial order
    return out


def groupby_aggregate(table: Table, by, aggs, ddof: int = 1) -> Table:
    """Group ``table`` by key columns ``by`` and aggregate.

    aggs: list of (value_col, op[, q]) with op in sum/count/min/max/mean/var/
    std/nunique/quantile/median.  Returns key columns + one column per agg
    named ``{col}_{op}``.  Null keys form their own group (reference
    semantics: comparators treat nulls as equal).

    Device OOM falls back to chunked streaming aggregation
    (exec/pipeline.GroupBySink) when every op decomposes through public
    partial aggregations (sum/count/min/max/mean/var/std)."""
    from ..exec.pipeline import GroupBySink, chunk_table
    from ..obs import plan as _plan
    from .common import run_with_oom_fallback

    def fallback(nc):
        _plan.annotate(route="chunked_sink", n_chunks=nc)
        sink = GroupBySink(by, aggs, ddof=ddof)
        for ch in chunk_table(table, nc):
            sink(ch)
        return sink.finalize()

    by_l = [by] if isinstance(by, str) else list(by)
    with _plan.node("groupby", by=tuple(by_l),
                    aggs=tuple((a[0], a[1]) for a in aggs
                               if isinstance(a, (list, tuple))
                               and len(a) >= 2)) as pn:
        # a stitch-deferred skew join feeds its PRE-stitch table here:
        # aggregation cannot observe row order/placement, so the skew
        # route's merge exchange is elided for join→groupby pipelines
        # (relational/skew.consume_unstitched, docs/skew.md)
        from .skew import consume_unstitched
        table = consume_unstitched(table)
        if pn:
            from ..core.table import DeferredTable
            # a DeferredTable input (fused join→groupby pushdown) stays
            # untouched: reading its counts or sampling its keys would
            # force the materialization the pushdown exists to avoid
            if not isinstance(table, DeferredTable):
                pn.set(rows_in=table.row_count)
                _plan.profile_keys(pn, table, by_l)
            else:
                pn.annotate(deferred_input=True)
        res = run_with_oom_fallback(
            lambda: _groupby_aggregate_impl(table, by, aggs, ddof),
            can_fallback=all(a[1] in GroupBySink._DECOMP for a in aggs),
            fallback=fallback, label="groupby", env=table.env)
        if pn and type(res) is Table:
            pn.set(rows_out=res.row_count)
        return res


def _groupby_aggregate_impl(table: Table, by, aggs, ddof: int = 1) -> Table:
    from ..exec.recovery import maybe_inject
    maybe_inject("groupby.device_oom")  # device-OOM ladder test point
    env = table.env
    by = [by] if isinstance(by, str) else list(by)
    specs = _normalize_aggs(aggs)
    # fused path: an unmaterialized inner-join result grouped by the join
    # keys aggregates straight off the pre-expansion sorted state
    # (relational/fused.py) — must run before any column access below,
    # which would materialize the join
    from ..obs import plan as _plan
    from .fused import try_join_groupby_pushdown
    pushed = try_join_groupby_pushdown(table, by, specs, ddof)
    if pushed is not None:
        _plan.annotate(route="fused_pushdown")
        return pushed
    # a skew-deferred join the pushdown could not serve still feeds its
    # PRE-stitch (split-layout) table here — aggregation cannot observe
    # row order/placement, so the stitch's merge exchange is skipped
    # (relational/skew.consume_unstitched, docs/skew.md)
    from .skew import consume_unstitched
    table = consume_unstitched(table, include_deferred=True)
    by_cols = [table.column(n) for n in by]
    val_cols = [table.column(c) for c, _, _, _ in specs]
    from ..core.column import HashedStrings
    for n, col in zip(by, by_cols):
        if col.type == LogicalType.LIST:
            raise InvalidError(
                f"groupby on list passthrough column {n!r} is not "
                "supported (codes are row ids, not value-equal)")
    for (c, op, _, _), col in zip(specs, val_cols):
        if col.type == LogicalType.LIST and op != "count":
            raise InvalidError(
                f"agg {op!r} not valid for list passthrough column {c!r}")
        if col.type == LogicalType.STRING and op not in ("count", "nunique",
                                                         "min", "max"):
            raise InvalidError(f"agg {op!r} not valid for string column {c!r}")
        if (col.type == LogicalType.STRING and op in ("min", "max")
                and isinstance(col.dictionary, HashedStrings)):
            raise InvalidError(
                f"agg {op!r} on high-cardinality hashed string column "
                f"{c!r}: hashed codes carry no lexical order")
    res_types, res_dicts = _result_types(specs, val_cols)
    res_names = [n for _, _, _, n in specs]
    all_assoc = all(op in gbk.ASSOCIATIVE for _, op, _, _ in specs)
    distributed = env.world_size > 1
    # grouped fast path: equal keys already contiguous per shard AND
    # co-located across shards (join/sort/groupby output) — one single-phase
    # pass, no shuffle, no rank sort
    grouped = (table.grouped_by is not None
               and tuple(by) == tuple(table.grouped_by))
    narrow = narrow32_flags(by_cols)

    if distributed and all_assoc and not grouped:
        # phase 1: local pre-combine (reference groupby.cpp:76-81), riding
        # the sort path when the columns lane-pack (see _raw_fn/vspec)
        _plan.annotate(route="combine_shuffle")
        by_datas, by_valids = col_arrays(by_cols)
        uniq_names = list(dict.fromkeys(c for c, _, _, _ in specs))
        val_map = tuple(uniq_names.index(c) for c, _, _, _ in specs)
        uval_cols = [table.column(c) for c in uniq_names]
        uval_datas = tuple(c.data for c in uval_cols)
        uval_valids = tuple(c.validity for c in uval_cols)
        vc = np.asarray(table.valid_counts, np.int32)
        ops_t = tuple(op for _, op, _, _ in specs)
        cap_full = max(table.capacity, 1)
        cspec = _plan_vspec(uval_cols, by_cols, narrow,
                            sum(len(INTER_NAMES[op]) for op in ops_t))
        cargs = (vc, by_datas, by_valids, uval_datas, uval_valids)

        def combine_call(sc):
            attempts = ([(f"sort+pad{p}",
                          lambda p=p: _combine_fn(env.mesh, ops_t, sc,
                                                  False, narrow, cspec,
                                                  val_map, p)(*cargs))
                         for p in (0, 1, 2)]
                        + [(f"sort+pad{pads}split{parts}",
                            lambda pads=pads, parts=parts: _combine_fn(
                                env.mesh, ops_t, sc, False, narrow, cspec,
                                val_map, pads, parts)(*cargs))
                           for pads, parts in ((0, 2), (1, 2), (0, 4))]) \
                if cspec is not None else []
            attempts.append(
                ("scatter",
                 lambda: _combine_fn(env.mesh, ops_t, sc, False, narrow,
                                     None, val_map)(*cargs)))
            return _pad_ladder(("combine", env.serial, ops_t, narrow, cspec),
                               attempts)

        # same first-sight/hysteresis segment-space discipline as the raw
        # path (multi-10M-segment programs have pathological compile times)
        seg_key1 = ("combine-seg", env.serial, ops_t, tuple(by), narrow,
                    cap_full, int(table.valid_counts.sum()))
        pred1 = _SEG_CACHE.get(seg_key1)
        if pred1 is not None and pred1 < cap_full:
            seg_cap = pred1
        elif pred1 is None and cap_full > _FIRST_SEG_CAP:
            seg_cap = _FIRST_SEG_CAP
        else:
            seg_cap = cap_full
        key_out, kval_out, inter_out, n_groups = combine_call(seg_cap)
        n_groups = host_array(n_groups).astype(np.int64)
        ng_cap1 = min(config.pow2ceil(int(n_groups.max()) if n_groups.size
                                      else 1), cap_full)
        if ng_cap1 > seg_cap:
            key_out, kval_out, inter_out, n_groups = combine_call(ng_cap1)
            n_groups = host_array(n_groups).astype(np.int64)
        _SEG_CACHE.put(seg_key1, ng_cap1)
        # intermediate table: keys + flat intermediate columns
        cols = {}
        for n, c, d, v in zip(by, by_cols, key_out, kval_out):
            cols[n] = Column(d, c.type, v, c.dictionary)
        inames_by_op = []
        for i, (_, op, _, _) in enumerate(specs):
            inames = []
            for iname, arr in zip(INTER_NAMES[op], inter_out[i]):
                cn = f"__i{i}_{iname}"
                cols[cn] = Column(arr, from_numpy_dtype(np.dtype(arr.dtype)),
                                  None, None)
                inames.append(cn)
            inames_by_op.append(inames)
        inter_table = _shrink(Table(cols, env, n_groups), n_groups)
        # phase 2: shuffle intermediates by key hash, final combine
        shuffled = shuffle_table(inter_table, by)
        s_by_datas, s_by_valids = col_arrays([shuffled.column(n) for n in by])
        inter_by_op = tuple(
            tuple(shuffled.column(cn).data for cn in inames)
            for inames in inames_by_op)
        vc2 = np.asarray(shuffled.valid_counts, np.int32)
        fin_cap = max(shuffled.capacity, 1)
        fargs = (vc2, s_by_datas, s_by_valids, inter_by_op)
        fattempts = [(f"sort+pad{p}",
                      lambda p=p: _final_fn(env.mesh, ops_t, fin_cap, ddof,
                                            narrow, p)(*fargs))
                     for p in (0, 1, 2)]
        for pads, parts in ((0, 2), (1, 2), (0, 4)):
            fattempts.append(
                (f"sort+pad{pads}split{parts}",
                 lambda pads=pads, parts=parts: _final_fn(
                     env.mesh, ops_t, fin_cap, ddof, narrow, pads, True,
                     parts)(*fargs)))
        fattempts.append(
            ("scatter", lambda: _final_fn(env.mesh, ops_t, fin_cap, ddof,
                                          narrow, 0, False)(*fargs)))
        key2, kval2, res_d, res_v, ng2 = _pad_ladder(
            ("final", env.serial, ops_t, narrow, ddof), fattempts)
        ng2 = host_array(ng2).astype(np.int64)
        out = _result_table(env, by, by_cols, key2, kval2, res_names, res_d,
                            res_v, res_types, res_dicts, ng2)
        out = _shrink(out, ng2)
        out.grouped_by = tuple(by)
        return out

    # non-associative ops (or local, or grouped input): co-locate raw rows
    _plan.annotate(route="grouped_fastpath" if grouped else "raw")
    work = table.project(list(dict.fromkeys(by + [c for c, _, _, _ in specs])))
    if distributed and not grouped:
        # the raw-row co-location shuffle is the one groupby route a heavy
        # key CAN concentrate on a single rank: non-decomposable aggs
        # (quantile/median/nunique) need every row of a group together, so
        # the join tier's split/duplicate-broadcast remedy does not apply
        # (associative aggs are skew-immune — per-group intermediates
        # collapse a heavy key to one row per shard before their shuffle).
        # Surface the hazard on the plan node so an EXPLAIN diff against
        # key_profile's est_rows_per_rank names WHY this plan is exposed
        # (docs/skew.md).
        _plan.annotate(skew_vulnerable=True)
        work = shuffle_table(work, by)
    by_datas, by_valids = col_arrays([work.column(n) for n in by])
    uniq_names = list(dict.fromkeys(c for c, _, _, _ in specs))
    val_map = tuple(uniq_names.index(c) for c, _, _, _ in specs)
    uval_cols = [work.column(c) for c in uniq_names]
    uval_datas = tuple(c.data for c in uval_cols)
    uval_valids = tuple(c.validity for c in uval_cols)
    vc = np.asarray(work.valid_counts, np.int32)
    spec_t = tuple((op, q) for _, op, q, _ in specs)
    cap_full = max(work.capacity, 1)

    def sum_fits_i32(col: Column) -> bool:
        b = col.bounds
        if b is None or col.data.dtype.kind not in ("i", "u"):
            return False
        m = max(abs(int(b[0])), abs(int(b[1])))
        return m * cap_full < (1 << 31)

    vnarrow = tuple(sum_fits_i32(work.column(c)) for c, _, _, _ in specs)

    # sort-path lane spec (non-grouped inputs): value + key columns ride the
    # rank sort as u32 lanes when all are laneable and the lane count is
    # modest (payload ~1.7 ns/row/lane vs ~12 ns/row per scatter-reduce)
    vspec = None
    if not grouped:
        n_inters = sum(len(INTER_NAMES[op]) for _, op, _, _ in specs
                       if op in gbk.ASSOCIATIVE)
        vspec = _plan_vspec(uval_cols, [work.column(n) for n in by], narrow,
                            max(n_inters, 1))
    # segment-capacity hysteresis: every reduction/scatter/gather in _raw_fn
    # runs over seg_cap slots, but the true group count is usually far below
    # row capacity — dispatch at the previous call's observed bucket and
    # re-dispatch at full capacity only when the observed count exceeds it
    # (n_groups comes from the gids themselves, so a mispredict is always
    # detected).  Steady-state pipelines (benchmarks, iterative queries) hit.
    seg_key = (env.serial, spec_t, tuple(by), grouped, narrow, ddof,
               cap_full, int(work.valid_counts.sum()))
    pred = _SEG_CACHE.get(seg_key)
    args = (vc, by_datas, by_valids, uval_datas, uval_valids)

    def raw_call(sc):
        # widened ladder (round 4): the scatter terminal compiles
        # pathologically at multi-M segment spaces (observed live: >55 min
        # at TPC-H SF5 Q18), so give the sort path more width-shifting
        # chances (pad2, pad1+split2, split4) before surrendering to it
        attempts = [(f"sort+pad{p}",
                     lambda p=p: _raw_fn(env.mesh, spec_t, sc, ddof, grouped,
                                         narrow, vnarrow, vspec, val_map,
                                         p)(*args))
                    for p in (0, 1, 2)]
        for pads, parts in ((0, 2), (1, 2), (0, 4)):
            attempts.append(
                (f"sort+pad{pads}split{parts}",
                 lambda pads=pads, parts=parts: _raw_fn(
                     env.mesh, spec_t, sc, ddof, grouped, narrow,
                     vnarrow, vspec, val_map, pads, True, parts)(*args)))
        attempts.append(
            ("scatter", lambda: _raw_fn(env.mesh, spec_t, sc, ddof, grouped,
                                        narrow, vnarrow, None, val_map, 0,
                                        False)(*args)))
        return _pad_ladder(("raw", env.serial, spec_t, grouped, narrow,
                            vnarrow, vspec), attempts)

    with timing.region("groupby.raw"):
        if pred is not None and pred < cap_full:
            seg_cap = pred
        elif pred is None and cap_full > _FIRST_SEG_CAP:
            # first sight of a large-cap groupby: dispatch at a modest
            # segment space — most groupbys have far fewer groups than
            # rows, and multi-10M-segment programs have pathological
            # XLA:TPU compile times (observed: 50+ min at a 33M segment
            # space that compiles in seconds at 1M).  A mispredict is
            # detected via n_groups and re-dispatched at the true bucket.
            seg_cap = _FIRST_SEG_CAP
        else:
            seg_cap = cap_full
        res = raw_call(seg_cap)
        n_groups = host_array(res[4]).astype(np.int64)
        ng_cap = min(config.pow2ceil(int(n_groups.max()) if n_groups.size
                                     else 1), cap_full)
        if ng_cap > seg_cap:
            res = raw_call(ng_cap)
        _SEG_CACHE.put(seg_key, ng_cap)
        key_out, kval_out, res_d, res_v = res[0], res[1], res[2], res[3]
    out = _result_table(env, by, by_cols, key_out, kval_out, res_names, res_d,
                        res_v, res_types, res_dicts, n_groups)
    out = _shrink(out, n_groups)
    out.grouped_by = tuple(by)
    return out


# ---------------------------------------------------------------------------
# trace-safety declarations (cylon_tpu.analysis.registry): groupby's two
# phases are pure-local shard programs separated by the hash shuffle — the
# jaxpr pass asserts no hidden collective, no row-scale i32→i64 widening,
# zero host callbacks.  docs/trace_safety.md.
# ---------------------------------------------------------------------------

def _decl_args(mesh, cap=1024):
    w = int(mesh.devices.size)
    S = jax.ShapeDtypeStruct
    vc = S((w,), np.int32)
    keys = (S((w * cap,), np.int64),)
    valids = (S((w * cap,), np.bool_),)
    vals = (S((w * cap,), np.float64),)
    return w, S, vc, keys, valids, vals


def _trace_combine(mesh):
    _w, _S, vc, keys, valids, vals = _decl_args(mesh)
    fn = _unwrap(_combine_fn(mesh, ("sum",), 256, False, (False,),
                             None, (0,)))
    return jax.make_jaxpr(fn)(vc, keys, valids, vals, valids)


def _trace_shrink(mesh):
    w, S, _vc, _k, _v, _vals = _decl_args(mesh)
    fn = _unwrap(_shrink_fn(mesh, 512))
    return jax.make_jaxpr(fn)(S((w * 1024,), np.float64))


def _trace_sink_finalize(mesh):
    w, S, _vc, _k, _v, _vals = _decl_args(mesh)
    fn = _unwrap(_sink_finalize_fn(mesh, ("mean", "var"), 1))
    cnt = S((w * 1024,), np.int64)
    f = S((w * 1024,), np.float64)
    return jax.make_jaxpr(fn)(cnt, f, cnt, f, f)


from ..analysis.registry import declare_builder, unwrap as _unwrap  # noqa: E402

declare_builder(f"{__name__}._combine_fn", _trace_combine,
                tags=("groupby",))
declare_builder(f"{__name__}._shrink_fn", _trace_shrink, tags=("groupby",))
declare_builder(f"{__name__}._sink_finalize_fn", _trace_sink_finalize,
                tags=("groupby", "stream"))
