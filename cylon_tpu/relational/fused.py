"""Fused join→groupby: aggregate through the join without materializing it.

The TPU realization of the reference's streaming operator DAG
(cpp/src/cylon/ops/ — ``DisJoinOP`` feeding downstream ops through queues,
SURVEY §2 C9): when a groupby's keys are exactly an inner join's keys and
every aggregation is multiplicity-algebraic, the per-group answer is
computable from the join's *pre-expansion sorted state* (phase 1) — the
output-space expansion (two ~15 ns/slot gathers over every output row, the
dominant join cost) never runs.

The algebra: an inner join's output rows for key group g are the L_g × R_g
cross product, so over the join output

  sum(c_left)   = S_g(c) · R_g          count(c_left) = C_g(c) · R_g
  mean(c_left)  = S_g(c) / C_g(c)       (multiplicity cancels)
  var/std       = moments scale by R_g; ddof applies to the full C·R count

with S/C the per-group masked sum/valid-count of c over the *left rows of
the sorted state* (symmetrically with L_g for right columns).  All of
S, C, L, R come out of the groupby engine's batched prefix-diff machinery
(ops/groupby.grouped_reduce) over the already-sorted state — a few cumsums
and ONE (seg_cap, L) gather.  min/max/quantile/nunique do not reduce to
prefix sums over the state and take the materialize path.

Trigger: ``groupby_aggregate`` calls :func:`try_join_groupby_pushdown`
first; it returns None (and the DeferredTable later materializes
transparently) unless every condition holds.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .. import config
from ..utils.cache import jit, program_cache
from ..core.column import Column
from ..core.dtypes import LogicalType
from ..core.table import DeferredTable, Table
from ..ctx.context import ROW_AXIS
from ..ops import groupby as gbk
from ..ops import lanes
from ..utils import timing
from ..utils.host import host_array
from .common import REP, ROW, BoundedCache

shard_map = jax.shard_map

#: ops whose join pushdown is exact multiplicity algebra
PUSHDOWN_OPS = {"sum", "count", "mean", "var", "std", "sumsq"}

#: callsite-signature -> last observed kept-group-count bucket
_SEG_CACHE = BoundedCache()


class JoinState(NamedTuple):
    """Pre-expansion inner-join state a DeferredTable carries for fused
    consumers (built in relational/join.py; device arrays stay sharded).

    Two producers emit this state: the monolithic deferred join (lane
    specs over the output-plan column lists) and the PACKED-PIECE join
    (relational/piece.py — lane specs are the piece sources' own specs
    and ``pl_s`` holds the sorted WINDOW lanes, so the groupby pushdown
    consumes range pieces without any piece ever materializing; columns
    the aggregation never reads are never unpacked).  The fused kernel is
    agnostic: ``plan``/``lspec``/``rspec`` are self-consistent in both."""
    vcl: np.ndarray      # left per-shard valid counts
    vcr: np.ndarray      # right per-shard valid counts
    idx_s: jax.Array     # (N,) concat-row index at each sorted position
    bnd: jax.Array       # (N,) key-boundary flags of the sorted state
    pl_s: tuple          # sorted payload lanes: left lanes ++ right lanes
    lspec: lanes.LaneSpec
    rspec: lanes.LaneSpec
    plan: tuple          # output plan entries parallel to names
    names: tuple
    types: tuple
    dicts: tuple
    key_names: tuple     # join-key output column names (== left_on)
    cap_l: int
    cap_r: int
    all_live: bool
    #: finalized skew-split plan (relational/skew.SkewPlan) when the join
    #: ran the adaptive heavy-key route: each heavy key's rows span a
    #: RANK GROUP, so the fused kernel's per-shard output rows are
    #: PARTIALS for those keys and resolve() must run the tiny
    #: heavy-partial combine (skew.combine_heavy_partials) before the
    #: result is final.  None for plain colocated joins.
    skew_plan: object = None
    #: with ``skew_plan``: materializes the SPLIT-layout join output
    #: WITHOUT the stitch — the pre-stitch table an order-insensitive
    #: consumer takes when the fused pushdown itself declines
    #: (relational/skew.consume_unstitched include_deferred leg)
    pre_thunk: object = None


def _col_entry(state: JoinState, name: str):
    """(side, lane-col-index) of output column ``name`` in the carried
    state; None when the column is not a plain carried l/r column."""
    try:
        i = state.names.index(name)
    except ValueError:
        return None
    e = state.plan[i]
    if e[0] in ("l", "r"):
        return e[0], e[1]
    return None


@program_cache()
def _fused_fn(mesh: Mesh, n_l: int, all_live: bool, lspec, rspec,
              vspecs: tuple, key_cols: tuple, key_narrow: tuple,
              seg_cap: int, ddof: int, pad_lanes: int = 0,
              gather_parts: int = 1, use_window: int = 0):
    """Per-shard fused join+groupby kernel.

    ``vspecs``: per aggregation (side, lane_col_idx, op); ``key_cols``:
    left lane-col index per groupby key.  Live rows form a sorted PREFIX
    (the row-liveness operand sorts padding last), so liveness is a
    position compare — no gather."""

    def per_shard(vcl, vcr, idx_s, bnd, pl_s):
        N = bnd.shape[0]
        pos = jnp.arange(N, dtype=jnp.int32)
        my = jax.lax.axis_index(ROW_AXIS)
        side_r = idx_s >= n_l
        if all_live:
            live = jnp.ones(N, bool)
        else:
            live = pos < (vcl[my] + vcr[my]).astype(jnp.int32)
        lefts_b = ~side_r & live
        rights_b = side_r & live
        lefts = lefts_b.astype(jnp.int32)
        rights = rights_b.astype(jnp.int32)
        first = bnd.astype(bool) | (pos == 0)
        s_l = jnp.cumsum(lefts).astype(jnp.int32)
        s_r = jnp.cumsum(rights).astype(jnp.int32)
        ebnd = jnp.concatenate([first[1:], jnp.ones(1, bool)])
        imax = jnp.int32(2**31 - 1)
        e_l = jax.lax.cummin(jnp.where(ebnd, s_l, imax), reverse=True)
        e_r = jax.lax.cummin(jnp.where(ebnd, s_r, imax), reverse=True)
        b_l = jax.lax.cummax(jnp.where(first, s_l - lefts, jnp.int32(0)))
        b_r = jax.lax.cummax(jnp.where(first, s_r - rights, jnp.int32(0)))
        l_grp = e_l - b_l        # own group's left count, per position
        r_grp = e_r - b_r
        keep = (l_grp > 0) & (r_grp > 0) & live
        kstart = first & keep
        kgid = jnp.cumsum(kstart.astype(jnp.int32)).astype(jnp.int32) - 1
        n_groups = (jnp.max(jnp.where(keep, kgid, -1)) + 1).astype(jnp.int32)
        starts = jnp.full(seg_cap, N, jnp.int32).at[
            jnp.where(kstart, kgid, jnp.int32(seg_cap))].set(pos, mode="drop")

        nl_lanes = lspec.n_lanes
        lmat = jnp.stack(pl_s[:nl_lanes], axis=1)
        ldat, lval = lanes.unpack_lanes(lspec, lmat)
        rmat = jnp.stack(pl_s[nl_lanes:], axis=1)
        rdat, rval = lanes.unpack_lanes(rspec, rmat)

        def value_of(side, ci):
            d = ldat[ci] if side == "l" else rdat[ci]
            v = lval[ci] if side == "l" else rval[ci]
            sidemask = lefts_b if side == "l" else rights_b
            vm = sidemask & keep
            if v is not None:
                vm = vm & v
            if jnp.issubdtype(d.dtype, jnp.floating):
                vm = vm & ~jnp.isnan(d)
            return d, vm

        ops_list, vals, masks = [], [], []
        for side, ci, op in vspecs:
            d, vm = value_of(side, ci)
            ops_list.append(op)
            vals.append(d)
            masks.append(vm)
        # the two multiplicity counts ride the same batched pass
        ops_list += ["count", "count"]
        vals += [s_l, s_l]
        masks += [lefts_b & keep, rights_b & keep]

        key_datas = [ldat[ci] for ci in key_cols]
        key_valids = [lval[ci] for ci in key_cols]
        inters, key_out, kval_out, wok = gbk.grouped_reduce(
            ops_list, vals, masks, starts, jnp.int32(N), key_datas,
            key_valids, seg_cap, key_narrow=key_narrow,
            pad_lanes=pad_lanes, gather_parts=gather_parts,
            use_window=use_window)
        l_cnt = inters[-2]["count"]
        r_cnt = inters[-1]["count"]

        res_d, res_v = [], []
        for i, (side, ci, op) in enumerate(vspecs):
            mult = (r_cnt if side == "l" else l_cnt)
            inter = inters[i]
            if op == "sum":
                s = inter["sum"]
                d, v = s * mult.astype(s.dtype), None
            elif op == "sumsq":
                s = inter["sumsq"]
                d, v = s * mult.astype(s.dtype), None
            elif op == "count":
                d, v = inter["count"] * mult, None
            elif op == "mean":
                d, v = gbk.finalize("mean", inter, ddof)
            else:  # var/std: moments scale by mult; ddof sees the full count
                scaled = {k: (a * mult.astype(a.dtype) if k != "count"
                              else a * mult) for k, a in inter.items()}
                d, v = gbk.finalize(op, scaled, ddof)
            res_d.append(d)
            res_v.append(v)
        # n_groups and the windowed-gather span flag ride ONE output so
        # the dispatch layer pays a single host pull (a second transfer
        # costs a full tunnel round trip per dispatch)
        meta = jnp.stack([n_groups, wok.astype(jnp.int32)]).reshape(2)
        return (tuple(key_out), tuple(kval_out), tuple(res_d), tuple(res_v),
                meta)

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, REP, ROW, ROW, ROW),
                             out_specs=(ROW, ROW, ROW, ROW, ROW)))


class _PendingFused:
    """A DISPATCHED (not yet pulled) fused join+groupby.  The first device
    program is already enqueued; :meth:`resolve` pulls its meta sidecar,
    handles seg-cap/window mispredicts (redispatching as needed) and
    builds the result Table — or returns None when the compile ladder is
    exhausted mid-resolve (caller falls back to the materialize path).

    Purpose: a range-partitioned pipeline consumes one fused groupby per
    piece, and each meta pull is a full host round trip (device idle, 8
    pieces x RTT adds ~0.5 s/iteration over the axon tunnel).  Begin/
    resolve lets the consumer enqueue piece i+1's program BEFORE pulling
    piece i's meta — one-deep software pipelining of dispatch vs pull
    (the reference's ops-DAG keeps pieces in flight the same way,
    cpp/src/cylon/ops/execution/execution.hpp:43 RoundRobin)."""

    __slots__ = ("_fn",)

    def __init__(self, fn):
        self._fn = fn

    def resolve(self):
        return self._fn()


def try_join_groupby_pushdown(table: Table, by: list, specs: list,
                              ddof: int):
    """Fused path when ``table`` is an unmaterialized inner-join result and
    the groupby reduces to multiplicity algebra over its sorted state.
    Returns the result Table, or None to take the normal path."""
    h = try_begin_join_groupby(table, by, specs, ddof)
    return h.resolve() if h is not None else None


def try_begin_join_groupby(table: Table, by: list, specs: list,
                           ddof: int):
    """Dispatch the fused join+groupby WITHOUT waiting for its meta pull.
    Returns a :class:`_PendingFused` (resolve() -> Table | None), or None
    when the fused path does not apply or its first compile crashed."""
    if not isinstance(table, DeferredTable) or table.materialized:
        return None
    state = table.op_state
    if not isinstance(state, JoinState):
        return None
    if tuple(by) != state.key_names:
        return None
    #: under a skew plan the heavy keys' per-shard fused rows are
    #: PARTIALS, combinable only for ops whose FINALIZED value is
    #: additive in the probe chunks (S_chunk·R over the members sums to
    #: S_g·R).  mean/var/std finalize to ratios of moments the members
    #: no longer share — those take the materialize path, where
    #: consume_unstitched still skips the stitch (docs/skew.md).
    skew_ops = ("sum", "count", "sumsq")
    if state.skew_plan is not None \
            and any(op not in skew_ops for _, op, _q, _n in specs):
        return None
    vspecs = []
    for col, op, _q, _name in specs:
        if op not in PUSHDOWN_OPS:
            return None
        ent = _col_entry(state, col)
        if ent is None:
            return None
        # string value columns carry dictionary CODES in the lanes;
        # aggregating codes would silently return garbage.  Bail to the
        # normal path, whose validation raises the same InvalidError the
        # materialized path does (only count is code-independent).
        if (state.types[state.names.index(col)] == LogicalType.STRING
                and op != "count"):
            return None
        spec = state.lspec if ent[0] == "l" else state.rspec
        if not spec.cols[ent[1]].lanes:
            return None   # carry-lite f64 column: not in the sorted lanes
        vspecs.append((ent[0], ent[1], op))
    key_cols, key_narrow = [], []
    for k in by:
        ent = _col_entry(state, k)
        if ent is None or ent[0] != "l" \
                or not state.lspec.cols[ent[1]].lanes:
            return None
        key_cols.append(ent[1])
        key_narrow.append(bool(state.lspec.cols[ent[1]].narrow))

    env = table.env
    from .groupby import _result_table, _shrink
    # result typing from the join output schema
    class _C:  # minimal stand-in with .type/.dictionary for _result_types
        def __init__(self, t, dc):
            self.type, self.dictionary = t, dc
    from .groupby import _result_types
    val_cols = [_C(state.types[state.names.index(c)],
                   state.dicts[state.names.index(c)]) for c, _, _, _ in specs]
    res_types, res_dicts = _result_types(specs, val_cols)
    by_cols = [_C(state.types[state.names.index(k)],
                  state.dicts[state.names.index(k)]) for k in by]
    res_names = [n for _, _, _, n in specs]

    cap_total = state.cap_l + state.cap_r
    args = (state.vcl, state.vcr, state.idx_s, state.bnd, state.pl_s)
    sig = (env.serial, tuple(by), tuple(vspecs), state.cap_l, state.cap_r,
           int(state.vcl.sum()), int(state.vcr.sum()), ddof)

    from .groupby import _FIRST_SEG_CAP, _is_compiler_crash, _pad_ladder
    from ..ops import pallas_gather as pg

    on_tpu = next(iter(env.mesh.devices.flat)).platform == "tpu"

    def _win_size(sc: int, dens: float) -> int:
        """Windowed-gather request for a dispatch at segment space ``sc``
        (0 = plain): TPU only, measured group density above the coverage
        floor, segment space big enough for the plain gather to hurt."""
        if not (on_tpu and config.WINDOWED_GATHER):
            return 0
        if dens < pg.MIN_DENSITY or sc < (1 << 20):
            return 0
        return pg.pick_window(dens)

    def call(sc, win):
        # same compiler-crash ladder as every other grouped_reduce dispatch
        # site: the windowed Pallas gather first (when eligible), then
        # dummy gather lanes to shift a SIGSEGV-ing lane width, then a
        # split gather — a still-crashing spec bails to the materialize path
        def disp(pad, parts=1, w=0):
            return _fused_fn(env.mesh, state.cap_l, state.all_live,
                             state.lspec, state.rspec, tuple(vspecs),
                             tuple(key_cols), tuple(key_narrow), sc,
                             ddof, pad, parts, w)(*args)

        attempts = []
        if win:
            attempts.append(("fused+win", lambda: disp(0, 1, win)))
        attempts += [(f"fused+pad{p}", lambda p=p: disp(p)) for p in (0, 1)]
        attempts.append(("fused+split2", lambda: disp(0, 2)))
        return _pad_ladder(("fused", env.serial, tuple(vspecs),
                            tuple(key_cols), tuple(key_narrow), bool(win)),
                           attempts)

    # first sight of a large state: dispatch at a modest segment space
    # (multi-10M-segment programs have pathological XLA:TPU compile
    # times); the returned n_groups detects a mispredict.  Cache value:
    # (seg bucket, windowed allowed, window size) — the window is
    # picked from the MEASURED per-shard group density (min across
    # shards) and a span overflow (win_ok False) permanently disables
    # the windowed gather for this callsite.
    with timing.region("groupby.fused"):
        pred = _SEG_CACHE.get(sig)
        if isinstance(pred, tuple):
            pred_seg, win_allowed, win = pred
        else:
            pred_seg, win_allowed, win = pred, True, 0
        if pred_seg is not None and pred_seg < cap_total:
            seg_cap = pred_seg
        elif pred_seg is None and cap_total > _FIRST_SEG_CAP:
            seg_cap = _FIRST_SEG_CAP
        else:
            seg_cap = config.pow2ceil(cap_total)
        if not win_allowed:
            win = 0
        try:
            res = call(seg_cap, win)     # ENQUEUED; meta not pulled yet
        except Exception as e:  # noqa: BLE001
            if _is_compiler_crash(e):
                return None   # ladder exhausted: materialize path handles it
            raise

    def _resolve():
        nonlocal res, seg_cap, win, win_allowed
        live = np.asarray(state.vcl, np.int64) + np.asarray(state.vcr,
                                                            np.int64)
        with timing.region("groupby.fused"):
            try:
                for _ in range(3):
                    meta = host_array(res[4]).astype(np.int64).reshape(-1, 2)
                    n_groups = meta[:, 0]
                    ng_cap = config.pow2ceil(int(n_groups.max())
                                             if n_groups.size else 1)
                    wok = (not win) or bool(np.all(meta[:, 1]))
                    if ng_cap <= seg_cap and wok:
                        break
                    if not wok:
                        win_allowed = False
                    seg_cap = max(seg_cap, ng_cap)
                    dens = float((n_groups / np.maximum(live, 1)).min()) \
                        if n_groups.size else 0.0
                    win = _win_size(seg_cap, dens) if win_allowed else 0
                    res = call(seg_cap, win)
            except Exception as e:  # noqa: BLE001
                if _is_compiler_crash(e):
                    return None   # caller falls back to materialize path
                raise
            _SEG_CACHE.put(sig, (ng_cap, win_allowed, win))
            key_out, kval_out, res_d, res_v = res[0], res[1], res[2], res[3]
        out = _result_table(env, by, by_cols, key_out, kval_out, res_names,
                            res_d, res_v, res_types, res_dicts, n_groups)
        out = _shrink(out, n_groups)
        if state.skew_plan is not None:
            # heavy-key member rows are partials: sum them onto the home
            # rank's row and drop the rest — the result equals the
            # unsplit fused plan's table, layout and all (docs/skew.md)
            from .skew import combine_heavy_partials
            out = combine_heavy_partials(out, list(by), res_names,
                                         state.skew_plan)
        else:
            out.grouped_by = tuple(by)
        return out

    return _PendingFused(_resolve)
