"""Shared plumbing for the table-level operators.

Mirrors the role of the reference's util layer for its Table ops
(cpp/src/cylon/util/arrow_utils.hpp, join/join_utils.hpp output assembly,
partition/partition.hpp): key-column canonicalization, string-dictionary
unification across tables (the reference compares strings via dual-table
comparators, arrow_comparator.hpp:238 — here both sides must share one code
space), per-shard liveness masks, and result-table assembly.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.column import Column
from ..core.dtypes import LogicalType, physical_np_dtype
from ..core.table import Table
from ..ctx.context import ROW_AXIS, CylonEnv
from ..status import CylonTypeError, InvalidError

ROW = P(ROW_AXIS)
REP = P()

#: distinct pad keys per table so padding rows never rank-equal across tables
PAD_L, PAD_R = 4, 5


class BoundedCache(dict):
    """Bounded FIFO mapping for callsite -> capacity-prediction caches
    (join output caps, groupby segment caps): oldest entry evicted at
    ``maxlen`` so varying input shapes cannot grow it without limit."""

    def __init__(self, maxlen: int = 512):
        super().__init__()
        self.maxlen = maxlen

    def put(self, key, value) -> None:
        if key not in self and len(self) >= self.maxlen:
            self.pop(next(iter(self)))
        self[key] = value


def is_oom(e: Exception) -> bool:
    """Device out-of-memory, as surfaced by XLA/PJRT.  Delegates to the
    fault-taxonomy boundary (exec/recovery — the ONE sanctioned place that
    string-matches runtime OOM text, lint rule TS105)."""
    from ..exec.recovery import is_oom as _is_oom
    return _is_oom(e)


def run_with_oom_fallback(primary, can_fallback: bool, fallback, label: str,
                          env=None):
    """``primary()`` with chunked-streaming capacity retries, routed
    through the rank-coherent consensus ladder
    (exec/recovery.run_with_recovery): faults are classified onto the
    typed taxonomy, multiprocess sessions agree on ONE status code before
    any retry/abort branch, and escalation is bounded and deterministic
    (OOM: ``fallback(4)`` then ``fallback(16)``; capacity overflow: one
    cap-halving step).  Non-fault errors always propagate.  Shared by
    join_tables, groupby_aggregate and set_operation — one retry policy,
    one coherence protocol.  Pass ``env`` so multiprocess sessions can
    run the consensus all-reduce over its mesh."""
    from ..exec.recovery import run_with_recovery
    return run_with_recovery(primary, can_fallback, fallback, label, env=env)


def sample_positions(n, m: int, cap: int) -> jax.Array:
    """m evenly spaced in-range row positions over a live prefix of traced
    length ``n`` (float stride: arange(m)*n would overflow int32 under
    x64=0).  Shared by sort splitter sampling and skew-key sampling."""
    stride = jnp.maximum(n, 1).astype(jnp.float32) / m
    idx = (jnp.arange(m, dtype=jnp.float32) * stride).astype(jnp.int32)
    return jnp.clip(idx, 0, cap - 1)


def live_mask(vc: jax.Array, cap: int) -> jax.Array:
    """Per-shard row-liveness mask (call inside shard_map): the first
    ``vc[my_rank]`` rows of the shard are real, the rest padding."""
    my = jax.lax.axis_index(ROW_AXIS)
    return jnp.arange(cap) < vc[my]


def valid_flag(col: Column):
    """Boolean filter payload of a bool column with null rows forced False
    (pandas/Arrow semantics: a null predicate never selects a row).  Every
    filter-on-bool-column call site must go through this."""
    flag = col.data
    if col.validity is not None:
        flag = flag & col.validity
    return flag


def fits_int32(c: Column) -> bool:
    """Host-known: this 64-bit integer column's value bounds fit int32, so
    any lane/operand packing may use one native 32-bit lane instead of a
    (hi, lo) pair.  Non-64-bit columns return False (already native)."""
    if c.data.dtype.itemsize != 8 or c.data.dtype.kind not in ("i", "u"):
        return False
    return c.bounds is not None and c.bounds[0] >= -(1 << 31) \
        and c.bounds[1] <= (1 << 31) - 1


def narrow32_flags(*col_lists) -> tuple:
    """Static per-key-column flags: True when every listed column's
    host-known bounds fit int32 (:func:`fits_int32`), so sort-operand
    packing may use one native operand instead of a (hi, lo) pair.  Pass
    the aligned key columns of all tables that will be ranked together."""
    n = len(col_lists[0])
    return tuple(all(fits_int32(cl[i]) for cl in col_lists)
                 for i in range(n))


def table_lane_spec(cols: list[Column]):
    """LaneSpec over a table's full column list (bounds-narrowed) — the
    static half of moving whole rows with ONE lane-matrix gather
    (ops/lanes.gather_columns) instead of one gather per column."""
    from ..ops import lanes
    return lanes.plan_lanes(tuple(str(c.data.dtype) for c in cols),
                            tuple(c.validity is not None for c in cols),
                            narrow32_flags(cols))


def col_arrays(cols: list[Column]):
    """Split columns into parallel (datas, valids) tuples; valids entries may
    be None (all-valid) — None is an empty pytree so it passes through jit."""
    return tuple(c.data for c in cols), tuple(c.validity for c in cols)


def promote_key_pair(a: Column, b: Column) -> tuple[Column, Column]:
    """Make a cross-table key pair comparable: unify string dictionaries,
    rescale decimals to a common scale, or promote numerics to a common
    logical type (the reference requires type-equal join keys; we
    additionally auto-promote numerics)."""
    if LogicalType.LIST in (a.type, b.type):
        raise CylonTypeError(
            "list passthrough columns cannot be keys (codes are row ids, "
            "not value-equal); they carry through joins as payload only")
    if (a.type == LogicalType.STRING) != (b.type == LogicalType.STRING):
        raise CylonTypeError(f"cannot join {a.type} with {b.type}")
    if a.type == LogicalType.STRING:
        return unify_dictionaries(a, b)
    if (a.type == LogicalType.DECIMAL) != (b.type == LogicalType.DECIMAL):
        raise CylonTypeError(
            f"cannot join {a.type} with {b.type}; rescale explicitly")
    if a.type == LogicalType.DECIMAL:
        return rescale_decimal_pair(a, b)
    if a.type == b.type:
        return a, b
    common = np.promote_types(physical_np_dtype(a.type), physical_np_dtype(b.type))
    lt = LogicalType(common.name) if common.name in LogicalType._value2member_map_ \
        else None
    if lt is None:
        raise CylonTypeError(f"no common key type for {a.type}/{b.type}")
    return a.cast(lt), b.cast(lt)


def rescale_decimal_pair(a: Column, b: Column) -> tuple[Column, Column]:
    """Bring two DECIMAL columns to one scale (the larger): the scaled
    ints then compare/join exactly.  10^Δ rescale is exact while the
    values stay within the representation's precision bound."""
    a, b = rescale_decimals_many([a, b])
    return a, b


def rescale_decimals_many(cs: list[Column]) -> list[Column]:
    """Bring N DECIMAL columns to ONE common scale in a single pass.
    The shared target is the largest scale, with precision covering EVERY
    column's 10^Δ-scaled digits (a coalesced outer-join key may hold any
    side's values under one declared type).  Past the representation's
    digit bound DecimalScale raises the clear error.

    One pass matters: pairwise promotion of [s=1, s=1, s=4] rescales only
    the columns it touches last, leaving earlier middles at a stale scale
    while the batch takes the final dictionary — a silent value corruption
    because decimals share int64 storage."""
    from ..core.column import DecimalScale
    scales = [c.dictionary for c in cs]
    if all(s == scales[0] for s in scales[1:]):
        return list(cs)
    scale = max(s.scale for s in scales)
    target = DecimalScale(max(s.precision + scale - s.scale for s in scales),
                          scale)

    def up(c: Column, own: DecimalScale) -> Column:
        f = 10 ** (scale - own.scale)
        bounds = ((c.bounds[0] * f, c.bounds[1] * f)
                  if c.bounds is not None else None)
        # python-int multiplier: jax weak typing keeps the data's dtype
        return Column(c.data * f if f != 1 else c.data, LogicalType.DECIMAL,
                      c.validity, target, bounds=bounds)

    return [up(c, s) for c, s in zip(cs, scales)]


def to_hashed_strings(c: Column) -> Column:
    """Re-code a sorted-dictionary string column into hashed-codes space
    (codes = stable 64-bit value hashes; core.column.HashedStrings) so it
    can meet a high-cardinality hashed column in a join/set op."""
    from ..core.column import HashedStrings
    if isinstance(c.dictionary, HashedStrings):
        return c
    from .. import native
    vals = np.asarray(c.dictionary, dtype=object)
    hashes = native.hash_strings(vals) if len(vals) \
        else np.zeros(0, np.uint64)
    remap = hashes.view(np.int64)
    data = jnp.take(jnp.asarray(remap),
                    jnp.clip(c.data, 0, max(len(vals) - 1, 0))) \
        if len(vals) else jnp.zeros_like(c.data, jnp.int64)
    return Column(data, LogicalType.STRING, c.validity,
                  HashedStrings(hashes, vals))


def unify_dictionaries(a: Column, b: Column) -> tuple[Column, Column]:
    """Re-code two dictionary-encoded string columns into one shared sorted
    dictionary (codes stay order-isomorphic to the strings, so sorts/joins on
    codes remain exact).  When either side is hashed (HashedStrings), both
    land in hashed-codes space — codes are globally comparable by
    construction (one hash function), only the decode lookups merge."""
    from ..core.column import HashedStrings
    if isinstance(a.dictionary, HashedStrings) \
            or isinstance(b.dictionary, HashedStrings):
        ah, bh = to_hashed_strings(a), to_hashed_strings(b)
        merged = ah.dictionary.merged_with(bh.dictionary)
        return (Column(ah.data, LogicalType.STRING, ah.validity, merged),
                Column(bh.data, LogicalType.STRING, bh.validity, merged))
    if a.dictionary is b.dictionary or (
            len(a.dictionary) == len(b.dictionary)
            and np.array_equal(a.dictionary, b.dictionary)):
        return a, b
    merged = np.unique(np.concatenate([a.dictionary, b.dictionary]))
    # recode maps stay numpy; jnp.take anchored on the committed codes runs
    # on the codes' device (no default-backend array creation)
    map_a = np.searchsorted(merged, a.dictionary).astype(np.int32)
    map_b = np.searchsorted(merged, b.dictionary).astype(np.int32)
    ca = Column(jnp.take(map_a, jnp.clip(a.data, 0, len(a.dictionary) - 1)),
                LogicalType.STRING, a.validity, merged)
    cb = Column(jnp.take(map_b, jnp.clip(b.data, 0, len(b.dictionary) - 1)),
                LogicalType.STRING, b.validity, merged)
    return ca, cb


def unify_dictionaries_many(cols: list[Column]) -> list[Column]:
    """N-way dictionary unification (used by concat / n-way set ops)."""
    from ..core.column import HashedStrings
    if any(isinstance(c.dictionary, HashedStrings) for c in cols):
        hashed = [to_hashed_strings(c) for c in cols]
        merged = hashed[0].dictionary
        for h in hashed[1:]:
            merged = merged.merged_with(h.dictionary)
        return [Column(h.data, LogicalType.STRING, h.validity, merged)
                for h in hashed]
    dicts = [c.dictionary for c in cols]
    if all(d is dicts[0] or np.array_equal(d, dicts[0]) for d in dicts[1:]):
        return list(cols)
    merged = np.unique(np.concatenate(dicts))
    out = []
    for c in cols:
        m = np.searchsorted(merged, c.dictionary).astype(np.int32)
        out.append(Column(jnp.take(m, jnp.clip(c.data, 0, len(c.dictionary) - 1)),
                          LogicalType.STRING, c.validity, merged))
    return out


def build_table(names, out_datas, out_valids, types, dicts,
                valid_counts: np.ndarray, env: CylonEnv,
                bounds=None) -> Table:
    """Assemble an output Table from kernel results (the static-shape analog
    of the reference's join_utils output builders).  ``bounds`` (optional,
    parallel to names) propagates host-known integer value bounds so
    downstream ops keep their narrow-lane fast paths."""
    cols = {}
    for i, (name, d, v, t, dc) in enumerate(
            zip(names, out_datas, out_valids, types, dicts)):
        b = bounds[i] if bounds is not None else None
        cols[name] = Column(d, t, v, dc, bounds=b)
    return Table(cols, env, np.asarray(valid_counts, np.int64))


def rebuild_like(items, out_datas, out_valids, valid_counts,
                 env: CylonEnv) -> Table:
    """build_table with schema (name/type/dictionary) taken from existing
    (name, Column) pairs — for ops that permute/filter rows of one table."""
    names = [n for n, _ in items]
    types = [c.type for _, c in items]
    dicts = [c.dictionary for _, c in items]
    return build_table(names, out_datas, out_valids, types, dicts,
                       valid_counts, env)


def check_same_env(a: Table, b: Table) -> CylonEnv:
    if a.env is not b.env and a.env.mesh is not b.env.mesh:
        raise InvalidError("tables belong to different CylonEnvs")
    return a.env


# ---------------------------------------------------------------------------
# key-value sampling for the heavy-hitter profiler (obs/plan, obs/sketch)
# ---------------------------------------------------------------------------

from ..utils.cache import jit, program_cache  # noqa: E402


@program_cache()
def _key_sample_fn(mesh, m: int, nkeys: int, with_valids: bool = False):
    """Evenly spaced per-shard sample of RAW key values plus the
    canonicalizing row hash — the sort-splitter sampling machinery
    (:func:`sample_positions`, relational/sort._sample_fn) applied to
    the profiler's needs: values NAME the hot keys (single integer-ish
    keys), the hash covers multi-column/float/string tuples with exactly
    the shuffle-routing predicate (ops/hashing.hash_rows).
    ``with_valids=True`` (the skew-split plan facade, relational/skew.py)
    additionally samples each key column's VALIDITY bit so a sampled
    tuple carries its full null structure — heavy NULL keys participate
    in the split exactly like values.  Pure-local per-shard program: no
    collective, no widening (jaxpr-gate registered)."""
    from ..ops import hashing

    def per_shard(vc, *args):
        datas = list(args[:nkeys])
        valids = list(args[nkeys:])
        cap = datas[0].shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        n = vc[my]
        h = hashing.hash_rows(datas, valids)
        idx = sample_positions(n, m, cap)
        live = jnp.full((m,), n > 0)
        out = tuple(d[idx] for d in datas)
        if with_valids:
            out += tuple(v[idx] for v in valids)
        return out + (h[idx], live)

    specs = (REP,) + (ROW,) * (2 * nkeys)
    nouts = nkeys * (2 if with_valids else 1) + 2
    return jit(jax.shard_map(per_shard, mesh=mesh, in_specs=specs,
                                 out_specs=(ROW,) * nouts))


def _key_value_repr(col: Column, vals: np.ndarray):
    """Host-side naming of sampled key values: raw numerics pass
    through; sorted-dictionary string codes decode to their strings;
    hashed-string codes stay codes (stable but opaque)."""
    if col.type == LogicalType.STRING:
        d = col.dictionary
        if isinstance(d, np.ndarray) and len(d):
            return d[np.clip(vals.astype(np.int64), 0, len(d) - 1)]
        # hashed-string codes (HashedStrings) fall through: stable but
        # opaque identities — decoding would need the value store lookup
    return vals


def sample_keys(table: Table, key_names: list, m: int | None = None,
                with_hashes: bool = False):
    """Sample ``table``'s key columns for the heavy-hitter profiler:
    returns ``(values, weights, total_rows)`` — a flat host array of
    sampled key identities (values for a single key column, row hashes
    for composite keys), a parallel weight array normalizing each
    shard's samples by its true row share (the join skew detector's
    weighting, relational/join._heavy_keys), and the global live row
    count.  ``with_hashes=True`` appends a fourth element: the routing
    hash (ops/hashing.hash_rows) aligned with ``values``, so the
    profiler can place each identity on its CURRENT partition
    (obs/plan.key_profile ``est_rows_per_rank``).  None for empty
    tables.  Armed-profiler path only: one small device program + one
    host pull."""
    from .. import config
    from ..utils.host import host_array

    env = table.env
    total = int(table.valid_counts.sum())
    if total == 0:
        return None
    w = env.world_size
    if m is None:
        m = config.SKEW_SAMPLE
    m = min(max(int(table.capacity), 1), int(m))
    cols = [table.column(n) for n in key_names]
    cap = cols[0].data.shape[0]
    datas = tuple(c.data for c in cols)
    valids = tuple(c.validity if c.validity is not None
                   else np.ones(cap, bool) for c in cols)
    outs = _key_sample_fn(env.mesh, m, len(cols))(
        np.asarray(table.valid_counts, np.int32), *datas, *valids)
    vals0 = host_array(outs[0]).reshape(w, m)
    hashes = host_array(outs[-2]).reshape(w, m)
    live = host_array(outs[-1]).reshape(w, m)
    if len(cols) == 1:
        raw = np.asarray(_key_value_repr(cols[0], vals0))
    else:
        raw = hashes
    vc = np.asarray(table.valid_counts, np.float64)
    values, weights, hlist = [], [], []
    for s in range(w):
        lv = raw[s][live[s]]
        if lv.size == 0:
            continue
        values.append(lv)
        hlist.append(hashes[s][live[s]])
        # each shard contributes its true row share, split evenly over
        # its samples — unweighted pooling would let a tiny shard's
        # keys dominate the estimate
        weights.append(np.full(lv.size, vc[s] / total / lv.size))
    if not values:
        return None
    out = (np.concatenate(values), np.concatenate(weights) * total, total)
    if with_hashes:
        out += (np.concatenate(hlist).astype(np.uint32),)
    return out


def sample_key_rows(table: Table, key_names: list, m: int | None = None):
    """Shard-weighted sample of FULL key tuples for the skew-split plan
    facade (relational/skew.py): returns ``(values, valids, hashes,
    weights, total_rows)`` — ``values``/``valids`` are per-key-column
    host arrays of the sampled raw data and validity bits (so a heavy
    tuple can be re-uploaded as an operand-space constant, nulls
    included), ``hashes`` the canonicalizing routing hash per sampled
    row, ``weights`` the same per-shard row-share normalization as
    :func:`sample_keys`.  None for empty tables.  One small pure-local
    device program + one host pull — no collective (the plan decision
    stays rank-uniform because the pull allgathers)."""
    from .. import config
    from ..utils.host import host_array

    env = table.env
    total = int(table.valid_counts.sum())
    if total == 0:
        return None
    w = env.world_size
    if m is None:
        m = config.SKEW_SAMPLE
    m = min(max(int(table.capacity), 1), int(m))
    cols = [table.column(n) for n in key_names]
    cap = cols[0].data.shape[0]
    nk = len(cols)
    datas = tuple(c.data for c in cols)
    valids = tuple(c.validity if c.validity is not None
                   else np.ones(cap, bool) for c in cols)
    outs = _key_sample_fn(env.mesh, m, nk, True)(
        np.asarray(table.valid_counts, np.int32), *datas, *valids)
    vals = [host_array(o).reshape(w * m) for o in outs[:nk]]
    vls = [host_array(o).reshape(w * m) for o in outs[nk:2 * nk]]
    hashes = host_array(outs[-2]).reshape(w * m)
    live = host_array(outs[-1]).reshape(w, m)
    vc = np.asarray(table.valid_counts, np.float64)
    keep = live.reshape(-1)
    if not keep.any():
        return None
    # each shard contributes its true row share split evenly over its
    # samples (the sample_keys weighting) — scaled to absolute rows
    per_shard_w = np.repeat(
        np.where(vc > 0, vc / np.maximum(m, 1), 0.0), m)
    return ([v[keep] for v in vals], [v[keep] for v in vls],
            hashes[keep], per_shard_w[keep], total)


def _trace_key_sample(mesh):
    w = int(mesh.devices.size)
    cap, S = 1024, jax.ShapeDtypeStruct
    fn = _key_sample_unwrap(_key_sample_fn(mesh, 64, 1))
    fnv = _key_sample_unwrap(_key_sample_fn(mesh, 64, 1, True))

    def both(vc, d, v):
        return fn(vc, d, v), fnv(vc, d, v)

    return jax.make_jaxpr(both)(S((w,), np.int32), S((w * cap,), np.int64),
                                S((w * cap,), np.bool_))


from ..analysis.registry import declare_builder as _declare_builder, \
    unwrap as _key_sample_unwrap  # noqa: E402

_declare_builder(f"{__name__}._key_sample_fn", _trace_key_sample,
                 tags=("profile",))
