"""Packed piece handles: window views over a lane-packed resident table.

The range-partitioned pipeline (exec/pipeline.py) packs each resident
sorted table into ONE u32 lane matrix (+ f64 side arrays) up front; every
range piece is then a contiguous per-shard window of that matrix.  The
seed materialized each window back into a full Table — dynamic-slice,
unpack EVERY column to full-width HBM arrays — only for the join to
immediately re-pack the keys into sort operands and the payloads into a
lane matrix.  That unpack→repack round trip was the single largest phase
of the pipelined join at the 125M-row operating point (BENCH_r05:
``pipe.piece_slice`` 3.74 s of 12.75 s).

:class:`PackedPiece` removes the wall: it is a pure HOST-SIDE descriptor
``(LaneSpec, lane matrix + f64 side arrays, per-shard starts/lens)`` —
producing one costs no device work at all.  ``join_tables`` /
``try_begin_join_groupby`` accept it in place of a materialized Table
(relational/join.py packed entry): the window slice and the lane unpack
happen *inside* the jitted join program, fused with key-operand
construction — keys unpack first, payload lanes ride the phase-1 sort and
unpack lazily in the carry/materialize stage, and columns the consumer
never reads are never unpacked (ops/lanes.unpack_column).

Ownership contract: the SOURCE (:class:`PieceSource`) owns the lane
matrix; every piece aliases it.  Pieces stay valid as long as the source's
arrays are alive — the pipeline holds the source for the whole range loop
and pieces never outlive it.  ``to_table()`` is the materialized escape
hatch (and the reference semantics the packed path is tested against).

Memory-pressure contract (exec/memory, docs/robustness.md): the packed
arrays register with the HBM ledger at pack time (spillable, LRU-touched
on every piece access).  A source whose registration has been EVICTED is
host-resident: ``packed()`` then uploads just the requested window back
to the device (``memory.upload_window`` — byte-identical to the resident
path's in-program dynamic slice, so results stay bit-equal) and the
pipelined range loop double-buffers those uploads against piece compute.
All residency changes go through the ledger (lint rule TS106).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .. import config
from ..core.column import Column
from ..core.table import Table
from ..ctx.context import ROW_AXIS
from ..utils.cache import jit, program_cache
from .common import REP, ROW

shard_map = jax.shard_map


@program_cache()
def _piece_pack_fn(mesh: Mesh, spec, pad: int, donate: bool = False):
    """Laneless (f64) columns pass ``None`` data — :func:`cylon_tpu.ops.
    lanes.pack_lanes` reads only their validity, and a dead donated
    buffer would otherwise be invalidated while :func:`_pad_rows_fn`
    still needs it (use-after-donate, lint rule TS108).  ``donate``
    consumes the caller's column buffers: the pack is their last reader
    (the pipeline deletes the sorted table right after), so XLA may
    free/reuse them DURING the pack instead of holding input + matrix
    live together."""
    from ..ops import lanes

    def per_shard(datas, valids):
        mat = lanes.pack_lanes(spec, list(datas), list(valids))
        if pad:
            mat = jnp.concatenate(
                [mat, jnp.zeros((pad, mat.shape[1]), mat.dtype)])
        return mat

    jit_kwargs = {"donate_argnums": (0, 1)} if donate else {}
    return jit(shard_map(per_shard, mesh=mesh, in_specs=(ROW, ROW),
                             out_specs=ROW), **jit_kwargs)


@program_cache()
def _pad_rows_fn(mesh: Mesh, pad: int, donate: bool = False):
    def per_shard(d):
        return jnp.concatenate([d, jnp.zeros((pad,), d.dtype)]) if pad else d

    jit_kwargs = {"donate_argnums": (0,)} if donate else {}
    return jit(shard_map(per_shard, mesh=mesh, in_specs=ROW,
                             out_specs=ROW), **jit_kwargs)


@program_cache()
def _piece_slice_fn(mesh: Mesh, spec, piece_cap: int):
    """Each shard's contiguous window [start, start+piece_cap) of the
    once-packed lane matrix (+f64 side arrays): dynamic slices, no gathers.
    The matrix is padded by the max piece capacity, so slices never clamp."""
    from ..ops import lanes

    has_mat = spec.n_lanes > 0
    n_f64 = sum(1 for cl in spec.cols if not cl.lanes)

    def per_shard(starts, *arrs):
        my = jax.lax.axis_index(ROW_AXIS)
        s = starts[my]
        if has_mat:
            mat, f64s = arrs[0], arrs[1:]
            sub = lanes.slice_lanes(spec, mat, s, piece_cap)
            datas, valids = lanes.unpack_lanes(spec, sub)
            datas, valids = list(datas), list(valids)
        else:
            f64s = arrs
            datas = [None] * len(spec.cols)
            valids = [None] * len(spec.cols)
        j = 0
        for i, cl in enumerate(spec.cols):
            if not cl.lanes:
                datas[i] = jax.lax.dynamic_slice(f64s[j], (s,), (piece_cap,))
                j += 1
        return tuple(datas), tuple(valids)

    in_specs = (REP,) + (ROW,) * (int(has_mat) + n_f64)
    return jit(shard_map(per_shard, mesh=mesh, in_specs=in_specs,
                             out_specs=(ROW, ROW)))


class PackedPiece:
    """A per-shard window ``[starts[s], starts[s]+piece_cap)`` over a
    :class:`PieceSource`'s packed arrays, of which the first ``lens[s]``
    rows are live.  Pure descriptor: holds references to the SOURCE's
    device arrays (no slice is dispatched until a consumer runs).

    ``meta`` entries are ``(name, LogicalType, dictionary, bounds)``
    parallel to ``spec.cols``.  ``reg`` (optional) is the source's HBM
    ledger registration — consumers LRU-touch it on access so eviction
    order tracks the piece loop (exec/memory)."""

    __slots__ = ("env", "spec", "meta", "arrs", "starts", "lens",
                 "piece_cap", "reg")

    def __init__(self, env, spec, meta, arrs, starts: np.ndarray,
                 lens: np.ndarray, piece_cap: int, reg=None):
        self.env = env
        self.spec = spec
        self.meta = meta
        self.arrs = arrs
        self.reg = reg
        self.starts = np.asarray(starts, np.int32)
        self.lens = np.asarray(lens, np.int64)
        self.piece_cap = int(piece_cap)
        if int(self.lens.max(initial=0)) > self.piece_cap:
            # a window wider than its static cap would silently truncate
            # live rows inside the jitted slice — typed so the consensus
            # ladder can take its deterministic cap-halving step
            from ..status import CapacityOverflowError
            raise CapacityOverflowError(
                f"piece window of {int(self.lens.max())} live rows exceeds "
                f"the pow2 piece cap {self.piece_cap}",
                site="join.piece_cap")

    @property
    def column_names(self) -> list[str]:
        return [n for n, _, _, _ in self.meta]

    @property
    def valid_counts(self) -> np.ndarray:
        return self.lens

    @property
    def row_count(self) -> int:
        return int(self.lens.sum())

    @property
    def capacity(self) -> int:
        return self.piece_cap

    def to_table(self) -> Table:
        """Materialize the window into a plain Table (dynamic slice + full
        unpack) — the reference path the packed consumers are exactly
        equal to, and the fallback when a consumer has no packed entry."""
        fn = _piece_slice_fn(self.env.mesh, self.spec, self.piece_cap)
        out_d, out_v = fn(self.starts, *self.arrs)
        cols = {}
        for (n, t, dc, nb), d, v in zip(self.meta, out_d, out_v):
            cols[n] = Column(d, t, v, dc, bounds=nb)
        return Table(cols, self.env, self.lens)


class PieceSource:
    """Range-piece provider over a resident sorted table: the table's
    columns pack into ONE u32 lane matrix up front (padded by the largest
    piece capacity so windows never clamp); each piece is then a pure
    host-side :class:`PackedPiece` window descriptor — producing a piece
    costs NO device work; the window slice runs inside whatever jitted
    program consumes it.  The caller should drop its reference to the
    source table: the matrix (plus f64 side arrays) carries everything.

    The packed arrays live in an HBM-ledger registration (spillable; see
    module docstring): ``scratch_bytes`` lets the caller fold the
    consumer's transient working set (sort operands,
    :func:`cylon_tpu.ops.pack.sort_operand_nbytes`) into the admission
    decision — the piece-cap-sizing consult of the ledger."""

    def __init__(self, table: Table, pad: int, drop: tuple = (),
                 scratch_bytes: int = 0, donate: bool = False):
        from ..exec import memory
        from .common import table_lane_spec
        self.env = table.env
        items = [(n, c) for n, c in table.columns.items() if n not in drop]
        cols = [c for _, c in items]
        self.spec = table_lane_spec(cols)
        self.meta = tuple(
            (n, c.type, c.dictionary,
             (min(c.bounds[0], 0), max(c.bounds[1], 0))
             if c.bounds is not None else None)
            for n, c in items)
        mesh = self.env.mesh
        w = self.env.world_size
        rows = w * (table.capacity + int(pad))
        # laneless (f64) columns contribute no data lane: their data rides
        # the side-array path (_pad_rows_fn) and must NOT enter the pack
        # program at all — under donation, a dead donated buffer would be
        # invalidated before _pad_rows_fn reads it (TS108)
        lane_datas = tuple(c.data if cl.lanes else None
                           for c, cl in zip(cols, self.spec.cols))
        valids = tuple(c.validity for c in cols)
        reuse = 0
        if donate:
            # donated column buffers are consumed by the pack programs —
            # the ledger must not count them AND the matrices they become
            # as simultaneous peak (docs/pipeline.md donation rules).
            # Count exactly what is donated: lane data + validity through
            # the pack program (only built when lanes exist), f64 side
            # data through the pad program.
            donated = list(c.data for c, cl in zip(cols, self.spec.cols)
                           if not cl.lanes)
            if self.spec.n_lanes:
                donated += [a for a in (*lane_datas, *valids)
                            if a is not None]
            reuse = sum(int(a.nbytes) for a in donated)
        # admission is SCHEDULER-mediated (lint rule TS109): the serving
        # tier attributes the bytes to the current tenant before routing
        # to the ledger's consensus-coherent admission path
        from ..exec import scheduler
        scheduler.admit_allocation(
            self.env, rows * memory.spec_row_bytes(self.spec),
            scratch=int(scratch_bytes), reuse=reuse)
        arrs = []
        if self.spec.n_lanes:
            arrs.append(_piece_pack_fn(mesh, self.spec, pad, donate)(
                lane_datas, valids))
        for c, cl in zip(cols, self.spec.cols):
            if not cl.lanes:
                arrs.append(_pad_rows_fn(mesh, pad, donate)(c.data))
        self._reg = memory.register("piece_src", tuple(arrs),
                                    spillable=True,
                                    sharding=self.env.sharding(),
                                    anchor=self)

    @property
    def arrs(self) -> tuple | None:
        """Device arrays while resident, None while spilled to host."""
        from ..exec import memory
        return memory.device_arrays(self._reg)

    @property
    def spilled(self) -> bool:
        return self._reg.spilled

    def packed(self, starts: np.ndarray, lens: np.ndarray,
               piece_cap: int | None = None) -> PackedPiece:
        from ..exec import memory
        if piece_cap is None:
            piece_cap = config.pow2ceil(max(int(lens.max(initial=0)), 1))
        memory.touch(self._reg)
        if not self._reg.spilled:
            return PackedPiece(self.env, self.spec, self.meta, self.arrs,
                               starts, lens, piece_cap, reg=self._reg)
        # host-resident source: upload ONLY this window (async dispatch —
        # the pipelined loop prefetches piece r+1 so this overlaps piece
        # r's compute); the uploaded arrays ARE the window, so the
        # in-program slice starts at 0
        w = self.env.world_size
        arrs = memory.upload_window(self._reg, np.asarray(starts, np.int64),
                                    int(piece_cap))
        return PackedPiece(self.env, self.spec, self.meta, arrs,
                           np.zeros(w, np.int32), lens, piece_cap,
                           reg=self._reg)

    def piece(self, starts: np.ndarray, lens: np.ndarray) -> Table:
        """Materialized window (seed behavior): slice + full unpack."""
        return self.packed(starts, lens).to_table()


# ---------------------------------------------------------------------------
# trace-safety declarations (cylon_tpu.analysis.registry): the piece
# programs are pure-local shard programs — slices and lane (un)packing
# only, no collectives, no host callbacks.  docs/trace_safety.md.
# ---------------------------------------------------------------------------

def _decl_spec():
    from ..ops import lanes
    # one nullable int32 lane column + one f64 side column: exercises the
    # matrix, the validity lane, and the side-array path without any
    # int64 lane reconstruction (which would trip JX203 by design)
    return lanes.plan_lanes(("int32", "float64"), (True, False))


def _trace_piece_pack(mesh):
    import jax as _jax
    w = int(mesh.devices.size)
    cap, S = 1024, _jax.ShapeDtypeStruct
    spec = _decl_spec()
    fn = _unwrap(_piece_pack_fn(mesh, spec, 8))
    # laneless (f64) data never enters the pack program (None leaf —
    # its buffer rides _pad_rows_fn and may be donated there, TS108)
    datas = (S((w * cap,), np.int32), None)
    valids = (S((w * cap,), np.bool_), None)
    return _jax.make_jaxpr(fn)(datas, valids)


def _trace_pad_rows(mesh):
    import jax as _jax
    w = int(mesh.devices.size)
    cap, S = 1024, _jax.ShapeDtypeStruct
    fn = _unwrap(_pad_rows_fn(mesh, 8))
    return _jax.make_jaxpr(fn)(S((w * cap,), np.float64))


def _trace_piece_slice(mesh):
    import jax as _jax
    w = int(mesh.devices.size)
    cap, S = 1024, _jax.ShapeDtypeStruct
    spec = _decl_spec()
    fn = _unwrap(_piece_slice_fn(mesh, spec, 256))
    starts = S((w,), np.int32)
    mat = S((w * (cap + 8), spec.n_lanes), np.uint32)
    f64 = S((w * (cap + 8),), np.float64)
    return _jax.make_jaxpr(fn)(starts, mat, f64)


from ..analysis.registry import declare_builder, unwrap as _unwrap  # noqa: E402

declare_builder(f"{__name__}._piece_pack_fn", _trace_piece_pack,
                tags=("pipeline",))
declare_builder(f"{__name__}._pad_rows_fn", _trace_pad_rows,
                tags=("pipeline",))
# keyed on (lane spec x pow2 piece capacity) — a wider legitimate family
# than the mesh-keyed builders, like join._count_fn
declare_builder(f"{__name__}._piece_slice_fn", _trace_piece_slice,
                tags=("pipeline",), retrace_budget=64)
