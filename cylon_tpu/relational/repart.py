"""Shuffle / repartition / slice / head / tail / concat.

TPU-native equivalents of the reference's redistribution operators:
``Shuffle`` (table.cpp:1298), ``Repartition`` (table.cpp:1481 — allgather row
counts -> compute send ranges -> order-preserving all-to-all, index math in
repartition.hpp:32-129), ``Slice``/``DistributedSlice`` (indexing/slice.cpp:31)
and ``DistributedHead/Tail`` (table.hpp:512-527), ``Merge``/concat.

Order preservation falls out of the exchange engine's (source rank, source
position) receive order (parallel/shuffle.py) exactly as in the reference's
``all_to_all_arrow_tables_preserve_order`` (table.cpp:182-190): each source
sends every destination a contiguous global range, so rank-major receive
order reconstructs global order.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .. import config
from ..utils.cache import jit, program_cache
from ..core.column import Column
from ..core.table import Table
from ..ctx.context import CylonEnv
from ..ops import sort as sortk
from ..parallel import shuffle
from ..status import InvalidError
from ..utils.host import host_array
from .common import ROW, REP, build_table, col_arrays, live_mask, \
    unify_dictionaries_many

shard_map = jax.shard_map


# ---------------------------------------------------------------------------
# column flattening for the exchange engine
# ---------------------------------------------------------------------------

@lru_cache(maxsize=config.PROGRAM_CACHE_SIZE)
def _pack_cols_fn(spec):
    from ..ops import lanes

    def fn(datas, valids):
        return lanes.pack_lanes(spec, list(datas), list(valids))

    return jit(fn)


@lru_cache(maxsize=config.PROGRAM_CACHE_SIZE)
def _unpack_cols_fn(spec):
    from ..ops import lanes

    def fn(mat):
        datas, valids = lanes.unpack_lanes(spec, mat)
        return (tuple(d for d in datas if d is not None),
                tuple(v for v in valids if v is not None))

    return jit(fn)


def _flatten_for_exchange(table: Table):
    """Table columns -> the exchange/collective payload tuple + a rebuild
    recipe.

    Every laneable column (data AND bit-packed validity — 32 nullable
    columns per u32 lane) packs into ONE (cap, L) u32 lane matrix via
    :mod:`cylon_tpu.ops.lanes`, so whatever moves the payload (all_to_all
    rounds, allgather, bcast) issues one collective/scatter chain per
    ROUND, not per column; host-known ``Column.bounds``
    (:func:`~.common.fits_int32`) narrow int64 columns to one lane.  f64
    columns (not laneable on TPU) travel as side arrays.  The matrix is a
    full-shard copy that lives until the move completes — the exchange's
    W·block memory bound applies to its per-round buffers, not to this
    staging copy."""
    from .common import table_lane_spec
    items = list(table.columns.items())
    cols = [c for _, c in items]
    spec = table_lane_spec(cols)
    flat = []
    if spec.n_lanes:
        flat.append(_pack_cols_fn(spec)(tuple(c.data for c in cols),
                                        tuple(c.validity for c in cols)))
    for c, cl in zip(cols, spec.cols):
        if not cl.lanes:
            flat.append(c.data)
    recipe = (spec, tuple((name, c.type, c.dictionary, c.bounds)
                          for name, c in items))
    return tuple(flat), recipe


def _rebuild(recipe, new_flat, valid_counts, env: CylonEnv) -> Table:
    spec, metas = recipe
    if spec.n_lanes:
        datas, valids = _unpack_cols_fn(spec)(new_flat[0])
        side = list(new_flat[1:])
    else:
        datas, valids = (), ()
        side = list(new_flat)
    datas, valids = list(datas), list(valids)
    cols = {}
    di = vi = si = 0
    for (name, t, dc, b), cl in zip(metas, spec.cols):
        if cl.lanes:
            d = datas[di]
            di += 1
        else:
            d = side[si]
            si += 1
        v = None
        if cl.valid_bit >= 0:
            v = valids[vi]
            vi += 1
        # exchanged rows are a permutation + zero padding of the input values
        nb = (min(b[0], 0), max(b[1], 0)) if b is not None else None
        cols[name] = Column(d, t, v, dc, bounds=nb)
    return Table(cols, env, np.asarray(valid_counts, np.int64))


# ---------------------------------------------------------------------------
# hash shuffle (reference Shuffle, table.cpp:1298)
# ---------------------------------------------------------------------------

def shuffle_table(table: Table, key_names,
                  owner: str = "shuffle.recv") -> Table:
    """Redistribute rows so equal keys land on the same shard (hash
    partitioning, reference MapToHashPartitions + ArrowAllToAll).
    ``owner`` labels the receive buffers' ledger registration —
    streaming appends pass ``stream.recv`` (cylon_tpu/stream)."""
    env = table.env
    # every distributed op shuffles, so this is the serving tier's
    # coarse interleave point for monolithic (non-pipelined) plans —
    # a no-op outside a scheduler (docs/serving.md)
    from ..exec import scheduler
    scheduler.maybe_yield()
    if env.world_size == 1:
        return table
    from ..obs import plan as _plan
    with _plan.node("shuffle", keys=tuple(key_names), owner=owner) as pn:
        if pn:
            pn.set(rows_in=table.row_count, rows_out=table.row_count)
        keys = [table.column(n) for n in key_names]
        datas, valids = col_arrays(keys)
        tgt = shuffle.hash_targets(env.mesh, datas, valids,
                                   table.valid_counts)
        counts = shuffle.count_targets(env.mesh, tgt)
        flat, recipe = _flatten_for_exchange(table)
        # hash shuffles run under join/groupby/setops OOM fallbacks: the
        # receive-budget guard may preempt a doomed allocation
        new_flat, new_valid = shuffle.exchange(env.mesh, tgt, counts, flat,
                                               guard=True, owner=owner)
        return _rebuild(recipe, new_flat, new_valid, env)


def exchange_by_targets(table: Table, tgt, counts: np.ndarray) -> Table:
    """Exchange with caller-computed per-row targets (range partition etc.)."""
    flat, recipe = _flatten_for_exchange(table)
    new_flat, new_valid = shuffle.exchange(table.env.mesh, tgt, counts, flat)
    return _rebuild(recipe, new_flat, new_valid, table.env)


# ---------------------------------------------------------------------------
# repartition (reference table.cpp:1481, repartition.hpp:94 index math)
# ---------------------------------------------------------------------------

@program_cache()
def _range_targets_fn(mesh: Mesh, cap: int):
    def per_shard(vc, offs, bounds, _probe):
        w = vc.shape[0]
        my = jax.lax.axis_index(shuffle.ROW_AXIS)
        # int32 iota for the mask only; gpos below stays int64 — GLOBAL
        # row positions legitimately exceed int32 at multi-billion rows
        mask = jnp.arange(cap, dtype=jnp.int32) < vc[my]
        gpos = offs[my] + jnp.arange(cap, dtype=jnp.int64)
        # bounds[d] = last global row index destined to d; first d with
        # bounds[d] >= gpos owns the row (empty destinations skip naturally)
        t = jnp.searchsorted(bounds, gpos, side="left").astype(jnp.int32)
        t = jnp.clip(t, 0, w - 1)
        return jnp.where(mask, t, jnp.int32(w))

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, REP, REP, ROW), out_specs=ROW))


def _order_preserving_targets(table: Table, dest_counts: np.ndarray):
    """Per-row destination ranks assigning global row i to the destination
    whose cumulative range contains i (reference DivideRowsEvenly /
    RowIndicesToAll, repartition.hpp:32-129)."""
    env = table.env
    vc = table.valid_counts
    offs = np.concatenate([[0], np.cumsum(vc)[:-1]]).astype(np.int64)
    bounds = np.cumsum(dest_counts).astype(np.int64) - 1
    probe = next(iter(table.columns.values())).data
    fn = _range_targets_fn(env.mesh, table.capacity)
    # sidecars stay numpy: jit places them per the shard_map specs on the
    # env's mesh; an eager jnp.asarray would land on the default backend
    return fn(np.asarray(vc, np.int32), offs, bounds, probe)


def even_partition_counts(total: int, w: int) -> np.ndarray:
    """The default order-preserving split: ``total`` global rows divided
    as evenly as possible over ``w`` partitions, earlier partitions
    taking the remainder — the host side of the
    :func:`_order_preserving_targets` index math (reference
    ``DivideRowsEvenly``, repartition.hpp:32).  Shared by
    :func:`repartition` and the elastic checkpoint re-shard path
    (``exec/checkpoint.py``), which re-blocks committed host pages onto
    a different-world mesh through the SAME split so a resharded resume
    lands on the exact distribution a fresh :func:`repartition` would
    produce."""
    total, w = int(total), int(w)
    base = total // w
    extra = total - base * w
    return np.asarray([base + (1 if i < extra else 0) for i in range(w)],
                      np.int64)


@program_cache()
def _pos_targets_fn(mesh: Mesh, cap: int):
    """Destination ranks from CALLER-COMPUTED global row positions (the
    skew stitch, relational/skew.py): destination d owns global positions
    [dof[d], dof[d] + dest[d]) of the even order-preserving layout.
    Padding rows (and the caller's ``total`` sentinel) route to the trash
    destination W.  Same index math as :func:`_range_targets_fn`, with
    ``pos`` replacing the contiguous ``offs[my] + iota`` range."""

    def per_shard(vc, bounds, pos):
        w = bounds.shape[0]
        my = jax.lax.axis_index(shuffle.ROW_AXIS)
        mask = jnp.arange(cap, dtype=jnp.int32) < vc[my]
        t = jnp.searchsorted(bounds, pos, side="left").astype(jnp.int32)
        t = jnp.clip(t, 0, w - 1)
        return jnp.where(mask, t, jnp.int32(w))

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, REP, ROW), out_specs=ROW))


@program_cache()
def _sort_flat_by_pos_fn(mesh: Mesh, cap: int, n_arrs: int):
    """Per-shard stable reorder of exchanged payload arrays by their
    received global positions: the exchange delivers (source rank, source
    position) order, but the stitch's positions interleave sources — one
    local sort puts every destination shard into global-position order.
    Padding slots (zeros from the exchange's receive buffers) sort last
    via the int64-max sentinel.  Pure-local; no collective."""
    big = jnp.int64(np.iinfo(np.int64).max)

    def per_shard(vc, pos, *arrs):
        my = jax.lax.axis_index(shuffle.ROW_AXIS)
        live = jnp.arange(cap, dtype=jnp.int32) < vc[my]
        key = jnp.where(live, pos, big)
        idx = jnp.arange(cap, dtype=jnp.int32)
        _, perm = jax.lax.sort((key, idx), num_keys=1, is_stable=True)
        return tuple(a[perm] for a in arrs)

    specs = (REP,) + (ROW,) * (1 + n_arrs)
    return jit(shard_map(per_shard, mesh=mesh, in_specs=specs,
                             out_specs=(ROW,) * n_arrs))


def place_by_global_pos(table: Table, pos, total: int) -> Table:
    """Redistribute ``table``'s rows onto the even order-preserving layout
    (:func:`even_partition_counts`) by their caller-computed GLOBAL row
    positions ``pos`` (device int64, the table's row layout; padding rows
    must carry the ``total`` sentinel).  Positions must be a permutation
    of [0, total).  The receiving shard locally sorts its rows by position
    (the exchange's (src, pos) receive order interleaves sources), so the
    result reads back in exactly position order — the merge half of the
    skew-split stitch's bit/order-equality contract
    (relational/skew.stitch_join_output, docs/skew.md)."""
    env = table.env
    w = env.world_size
    total = int(total)
    if total == 0 or not table.column_count:
        return table
    from ..utils import timing
    dest = even_partition_counts(total, w)
    bounds = np.cumsum(dest).astype(np.int64) - 1
    vc32 = np.asarray(table.valid_counts, np.int32)
    cap = max(table.capacity, 1)
    with timing.region("place.targets"):
        tgt = _pos_targets_fn(env.mesh, cap)(vc32, bounds, pos)
        counts = shuffle.count_targets(env.mesh, tgt)
    with timing.region("place.exchange"):
        flat, recipe = _flatten_for_exchange(table)
        new_flat, new_valid = shuffle.exchange(env.mesh, tgt, counts,
                                               flat + (pos,))
    if not np.array_equal(np.asarray(new_valid, np.int64), dest):
        raise InvalidError(
            f"place_by_global_pos: received counts {list(new_valid)} do "
            f"not match the even layout {list(dest)} — positions are not "
            "a permutation of the claimed total")
    with timing.region("place.sort"):
        out_cap = new_flat[0].shape[0] // w
        fn = _sort_flat_by_pos_fn(env.mesh, out_cap, len(new_flat) - 1)
        sorted_flat = fn(np.asarray(new_valid, np.int32), new_flat[-1],
                         *new_flat[:-1])
    return _rebuild(recipe, sorted_flat, new_valid, env)


def repartition(table: Table, rows_per_partition=None) -> Table:
    """Redistribute preserving global row order; default = even split."""
    from ..obs import plan as _plan
    env = table.env
    w = env.world_size
    total = table.row_count
    if rows_per_partition is None:
        dest = even_partition_counts(total, w)
    else:
        dest = np.asarray(rows_per_partition, np.int64)
        if dest.shape != (w,) or dest.sum() != total:
            raise InvalidError(
                f"rows_per_partition must hold {w} counts summing to {total}")
    if w == 1 or not table.column_count:
        return table
    if np.array_equal(dest, table.valid_counts):
        return table
    with _plan.node("repartition", order_preserving=True) as pn:
        if pn:
            pn.set(rows_in=total, rows_out=total)
        tgt = _order_preserving_targets(table, dest)
        # count matrix is fully determined host-side: source s's global
        # range [offs, offs+vc) intersected with each destination range
        soff = np.concatenate([[0], np.cumsum(table.valid_counts)[:-1]])
        dof = np.concatenate([[0], np.cumsum(dest)[:-1]])
        counts = np.zeros((w, w), np.int64)
        for s in range(w):
            lo, hi = soff[s], soff[s] + table.valid_counts[s]
            for d in range(w):
                counts[s, d] = max(
                    0, min(hi, dof[d] + dest[d]) - max(lo, dof[d]))
        return exchange_by_targets(table, tgt, counts)


@program_cache()
def _repad_fn(mesh: Mesh, cap: int, new_cap: int):
    def per_shard(d):
        if new_cap <= cap:
            return d[:new_cap]
        pad = jnp.zeros((new_cap - cap,) + d.shape[1:], d.dtype)
        return jnp.concatenate([d, pad])

    return jit(shard_map(per_shard, mesh=mesh, in_specs=ROW,
                             out_specs=ROW))


def repad_table(table: Table, new_cap: int) -> Table:
    """Change per-shard capacity without moving rows (valid prefixes must fit
    the new capacity)."""
    cap = table.capacity
    if new_cap == cap:
        return table
    if int(table.valid_counts.max(initial=0)) > new_cap:
        raise InvalidError(f"valid rows exceed new capacity {new_cap}")
    fn = _repad_fn(table.env.mesh, cap, new_cap)
    cols = {}
    for n, c in table.columns.items():
        d = fn(c.data)
        v = fn(c.validity) if c.validity is not None else None
        # pad rows are zeros -> widen bounds to include 0
        b = c.bounds
        if b is not None and new_cap > cap:
            b = (min(b[0], 0), max(b[1], 0))
        cols[n] = Column(d, c.type, v, c.dictionary, bounds=b)
    return Table(cols, table.env, table.valid_counts)


# ---------------------------------------------------------------------------
# slice / head / tail (reference indexing/slice.cpp:31, table.hpp:512-527)
# ---------------------------------------------------------------------------

@program_cache()
def _compact_range_fn(mesh: Mesh, cap: int, out_cap: int, spec):
    from ..ops import lanes

    def per_shard(vc, offs, lo, hi, datas, valids):
        my = jax.lax.axis_index(shuffle.ROW_AXIS)
        mask = jnp.arange(cap) < vc[my]
        gpos = offs[my] + jnp.arange(cap, dtype=jnp.int64)
        keep = mask & (gpos >= lo) & (gpos < hi)
        idx, _total = sortk.compact_by_flag(keep, out_cap)
        # ONE lane-matrix gather for all columns (+ f64 side gathers)
        return lanes.gather_columns(spec, list(datas), list(valids), idx)

    return jit(shard_map(
        per_shard, mesh=mesh,
        in_specs=(REP, REP, REP, REP, ROW, ROW), out_specs=(ROW, ROW)))


def slice_table(table: Table, offset: int, length: int) -> Table:
    """Global-order row range [offset, offset+length) (distribution-preserving
    like the reference's DistributedSlice — each rank keeps its overlap)."""
    env = table.env
    vc = table.valid_counts
    offs = np.concatenate([[0], np.cumsum(vc)[:-1]]).astype(np.int64)
    lo, hi = int(offset), int(offset) + int(length)
    kept = np.clip(np.minimum(offs + vc, hi) - np.maximum(offs, lo), 0, None)
    out_cap = config.pow2ceil(int(kept.max()) if kept.size else 1)
    cols = list(table.columns.items())
    datas = tuple(c.data for _, c in cols)
    valids = tuple(c.validity for _, c in cols)
    from .common import table_lane_spec
    fn = _compact_range_fn(env.mesh, table.capacity, out_cap,
                           table_lane_spec([c for _, c in cols]))
    out_d, out_v = fn(np.asarray(vc, np.int32), offs,
                      np.int64(lo), np.int64(hi), datas, valids)
    names = [n for n, _ in cols]
    types = [c.type for _, c in cols]
    dicts = [c.dictionary for _, c in cols]
    return build_table(names, out_d, out_v, types, dicts, kept, env)


def head(table: Table, n: int) -> Table:
    return slice_table(table, 0, n)


def tail(table: Table, n: int) -> Table:
    total = table.row_count
    n = min(n, total)
    return slice_table(table, total - n, n)


# ---------------------------------------------------------------------------
# row filter (reference: compute.pyx filter path — table[bool_mask])
# ---------------------------------------------------------------------------

@program_cache()
def _filter_count_fn(mesh: Mesh, cap: int):
    def per_shard(vc, flag):
        mask = live_mask(vc, cap)
        return jnp.sum(flag & mask).astype(jnp.int32).reshape(1)

    return jit(shard_map(per_shard, mesh=mesh, in_specs=(REP, ROW),
                             out_specs=ROW))


@program_cache()
def _filter_mat_fn(mesh: Mesh, cap: int, out_cap: int, spec):
    from ..ops import lanes

    def per_shard(vc, flag, datas, valids):
        mask = live_mask(vc, cap)
        idx, _ = sortk.compact_by_flag(flag & mask, out_cap)
        # ONE lane-matrix gather for all columns (+ f64 side gathers)
        return lanes.gather_columns(spec, list(datas), list(valids), idx)

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, ROW, ROW, ROW),
                             out_specs=(ROW, ROW)))


def filter_table(table: Table, flag) -> Table:
    """Keep rows whose boolean flag is set (flag: device bool array with the
    table's row layout).  Row order preserved; distribution keeps each row on
    its shard (like the reference's local filter ops)."""
    from .common import rebuild_like
    env = table.env
    cap = max(table.capacity, 1)
    vc = np.asarray(table.valid_counts, np.int32)
    counts = host_array(_filter_count_fn(env.mesh, cap)(vc, flag)
                        ).astype(np.int64)
    out_cap = config.pow2ceil(int(counts.max()) if counts.size else 1)
    items = list(table.columns.items())
    datas = tuple(c.data for _, c in items)
    valids = tuple(c.validity for _, c in items)
    from .common import table_lane_spec
    spec = table_lane_spec([c for _, c in items])
    out_d, out_v = _filter_mat_fn(env.mesh, cap, out_cap, spec)(vc, flag,
                                                                datas, valids)
    return rebuild_like(items, out_d, out_v, counts, env)


# ---------------------------------------------------------------------------
# concat (reference Merge/concat, frame.py:2295)
# ---------------------------------------------------------------------------

@program_cache()
def _concat_fn(mesh: Mesh, caps: tuple, out_cap: int, with_valid: tuple):
    """Per-shard append of k tables' live prefixes: each table's FULL padded
    block is block-copied (``dynamic_update_slice`` — contiguous, ~1 ns/row
    vs ~15 ns/row for the scatter this replaces) at its shard's running
    offset, in ascending table order so a block's trailing padding lands in
    the NEXT table's region and is overwritten by its copy.  The scratch
    buffer is ``out_cap + max(caps)`` so the last block never clamps; the
    result is its ``out_cap`` prefix.  Output padding rows are whatever the
    last block's padding held — callers rely on the valid-prefix contract,
    never on zeroed padding."""
    k = len(caps)
    pad_cap = out_cap + max(caps)

    def per_shard(vcs, datas_by_t, valids_by_t):
        my = jax.lax.axis_index(shuffle.ROW_AXIS)
        off = jnp.zeros((), jnp.int32)
        ncols = len(datas_by_t[0])
        outs = [jnp.zeros((pad_cap,), datas_by_t[0][c].dtype)
                for c in range(ncols)]
        outv = [jnp.zeros((pad_cap,), bool) if with_valid[c] else None
                for c in range(ncols)]
        for t in range(k):
            cap_t = caps[t]
            for c in range(ncols):
                outs[c] = jax.lax.dynamic_update_slice(
                    outs[c], datas_by_t[t][c], (off,))
                if with_valid[c]:
                    v = valids_by_t[t][c]
                    v = v if v is not None else jnp.ones(cap_t, bool)
                    outv[c] = jax.lax.dynamic_update_slice(outv[c], v, (off,))
            off = off + vcs[t][my].astype(jnp.int32)
        return (tuple(o[:out_cap] for o in outs),
                tuple(v[:out_cap] if v is not None else None for v in outv))

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, ROW, ROW), out_specs=(ROW, ROW)))


def concat_tables(tables: list[Table]) -> Table:
    """Row-wise concatenation. Per-shard append order follows input order
    (the reference's per-rank local Merge has the same per-partition
    semantics)."""
    if not tables:
        raise InvalidError("concat of zero tables")
    if len(tables) == 1:
        return tables[0]
    env = tables[0].env
    names = tables[0].column_names
    for t in tables[1:]:
        if t.column_names != names:
            raise InvalidError(f"concat schema mismatch: {t.column_names} vs {names}")
    # unify string dictionaries / promote numerics column-wise
    from ..core.dtypes import LogicalType
    from .common import promote_key_pair
    col_sets = []
    for n in names:
        cs = [t.column(n) for t in tables]
        if cs[0].type == LogicalType.STRING:
            cs = unify_dictionaries_many(cs)
        elif all(c.type == LogicalType.LIST for c in cs):
            # merge the passthrough value stores; later tables' codes
            # shift by the cumulative store length
            from ..core.column import PassthroughValues
            vals = [c.dictionary.values for c in cs]
            offs = np.cumsum([0] + [len(v) for v in vals[:-1]])
            merged = PassthroughValues(np.concatenate(vals)
                                       if vals else np.zeros(0, object))
            hi = max(len(merged) - 1, 0)
            cs = [Column(c.data + int(o), LogicalType.LIST, c.validity,
                         merged, bounds=(0, hi))
                  for c, o in zip(cs, offs)]
        elif all(c.type == LogicalType.DECIMAL for c in cs):
            # ONE pass to the common scale: pairwise promotion would leave
            # middle columns at a stale scale while the output dictionary
            # takes the final (largest) one — silent corruption, since
            # decimals share int64 storage
            from .common import rescale_decimals_many
            cs = rescale_decimals_many(cs)
        elif len({c.type for c in cs}) == 1:
            pass
        else:
            for i in range(1, len(cs)):
                cs[0], cs[i] = promote_key_pair(cs[0], cs[i])
            # pairwise promotion converges on cs[0]'s final type; bring
            # every middle column to it in a second sweep (mixed numeric
            # middles otherwise keep a stale dtype)
            final = cs[0].type
            cs = [c if c.type == final else promote_key_pair(cs[0], c)[1]
                  for c in cs]
        col_sets.append(cs)
    w = env.world_size
    vcs = [t.valid_counts for t in tables]
    new_valid = np.sum(vcs, axis=0)
    out_cap = config.pow2ceil(int(new_valid.max()) if w else 1)
    caps = tuple(t.capacity for t in tables)
    with_valid = tuple(any(cs[i].validity is not None for i in range(len(tables)))
                       for cs in col_sets)
    datas_by_t = tuple(tuple(col_sets[c][t].data for c in range(len(names)))
                       for t in range(len(tables)))
    valids_by_t = tuple(tuple(col_sets[c][t].validity for c in range(len(names)))
                        for t in range(len(tables)))
    fn = _concat_fn(env.mesh, caps, out_cap, with_valid)
    vcs_host = tuple(np.asarray(v, np.int32) for v in vcs)
    out_d, out_v = fn(vcs_host, datas_by_t, valids_by_t)
    types = [cs[0].type for cs in col_sets]
    dicts = [cs[0].dictionary for cs in col_sets]
    # merged bounds (∪ {0}: output padding may expose any block's padding)
    bounds = []
    for cs in col_sets:
        bs = [c.bounds for c in cs]
        bounds.append((min(min(b[0] for b in bs), 0),
                       max(max(b[1] for b in bs), 0))
                      if all(b is not None for b in bs) else None)
    return build_table(names, out_d, out_v, types, dicts, new_valid, env,
                       bounds=bounds)


# ---------------------------------------------------------------------------
# trace-safety declarations (cylon_tpu.analysis.registry) — pure-local
# shard programs (the exchange rides parallel/shuffle.py); no collective
# may appear.  docs/trace_safety.md.
# ---------------------------------------------------------------------------

def _trace_range_targets(mesh):
    w = int(mesh.devices.size)
    cap = 1024
    S = jax.ShapeDtypeStruct
    fn = _unwrap(_range_targets_fn(mesh, cap))
    # dtypes mirror the production caller (_order_preserving_targets):
    # int32 valid counts, int64 offsets/bounds — the gate must verify the
    # dtype specialization that actually runs
    return jax.make_jaxpr(fn)(S((w,), np.int32), S((w,), np.int64),
                              S((w,), np.int64), S((w * cap,), np.int64))


def _trace_pos_targets(mesh):
    w = int(mesh.devices.size)
    cap = 1024
    S = jax.ShapeDtypeStruct
    fn = _unwrap(_pos_targets_fn(mesh, cap))
    return jax.make_jaxpr(fn)(S((w,), np.int32), S((w,), np.int64),
                              S((w * cap,), np.int64))


def _trace_sort_flat_by_pos(mesh):
    w = int(mesh.devices.size)
    cap = 1024
    S = jax.ShapeDtypeStruct
    fn = _unwrap(_sort_flat_by_pos_fn(mesh, cap, 2))
    return jax.make_jaxpr(fn)(S((w,), np.int32), S((w * cap,), np.int64),
                              S((w * cap, 3), np.uint32),
                              S((w * cap,), np.float64))


from ..analysis.registry import declare_builder, unwrap as _unwrap  # noqa: E402

declare_builder(f"{__name__}._range_targets_fn", _trace_range_targets,
                tags=("repart", "shuffle"))
declare_builder(f"{__name__}._pos_targets_fn", _trace_pos_targets,
                tags=("repart", "skew"))
declare_builder(f"{__name__}._sort_flat_by_pos_fn", _trace_sort_flat_by_pos,
                tags=("repart", "skew"))
