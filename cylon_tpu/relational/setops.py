"""Table-level set ops (union/intersect/subtract), unique, equals.

TPU-native equivalents of the reference's row-set operators — ``Union``
(table.cpp:925), ``Subtract`` (:997), ``Intersect`` (:1051) and their
distributed wrappers (:1152-1166, shuffle both then local), ``Unique``
(:1306) / ``DistributedUnique`` (:1376), and ``Equals``/``DistributedEquals``
(:1389/:1440 — repartition-to-match then compare).

The reference builds ska::bytell hash sets over row comparators; here rows of
both tables are dense-ranked together per shard (ops/pack.py — the dual-table
comparator analog) and membership/uniqueness become segment min/max logic
(ops/setops.py), followed by a static-capacity compaction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .. import config
from ..utils.cache import jit, program_cache
from ..core.column import Column
from ..core.dtypes import LogicalType
from ..core.table import Table
from ..ops import pack
from ..ops import setops as setk
from ..ops import sort as sortk
from ..status import InvalidError
from ..utils.host import host_array
from .common import (PAD_L, REP, ROW, check_same_env, col_arrays, live_mask,
                     promote_key_pair, rebuild_like)
from .repart import repartition, shuffle_table

shard_map = jax.shard_map


# ---------------------------------------------------------------------------
# unique (drop_duplicates)
# ---------------------------------------------------------------------------

def _unique_flags_per_shard(vc, key_datas, key_valids, keep: str):
    cap = key_datas[0].shape[0]
    mask = live_mask(vc, cap)
    ko = pack.key_operands(list(key_datas), list(key_valids), row_mask=mask,
                           pad_key=PAD_L)
    gids, _ = pack.dense_rank(ko)
    return setk.unique_flags(gids, mask, keep), mask


@program_cache()
def _unique_count_fn(mesh: Mesh, keep: str):
    def per_shard(vc, key_datas, key_valids):
        flags, _ = _unique_flags_per_shard(vc, key_datas, key_valids, keep)
        return jnp.sum(flags, dtype=jnp.int32).reshape(1)

    return jit(shard_map(per_shard, mesh=mesh, in_specs=(REP, ROW, ROW),
                             out_specs=ROW))


@program_cache()
def _unique_mat_fn(mesh: Mesh, keep: str, out_cap: int, spec):
    from ..ops import lanes

    def per_shard(vc, key_datas, key_valids, datas, valids):
        flags, _ = _unique_flags_per_shard(vc, key_datas, key_valids, keep)
        idx, _total = sortk.compact_by_flag(flags, out_cap)
        # ONE lane-matrix gather for all columns (+ f64 side gathers)
        return lanes.gather_columns(spec, list(datas), list(valids), idx)

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, ROW, ROW, ROW, ROW),
                             out_specs=(ROW, ROW)))


def unique_table(table: Table, subset=None, keep: str = "first") -> Table:
    """Drop duplicate rows (by ``subset`` columns, default all).  Distributed:
    shuffle by subset hash so equal rows co-locate; within a shard the
    (source rank, source position) receive order makes keep=first/last pick
    the *globally* first/last occurrence."""
    env = table.env
    subset = list(subset) if subset is not None else table.column_names
    if keep not in ("first", "last"):
        raise InvalidError("keep must be 'first' or 'last'")
    from ..core.dtypes import LogicalType
    for n in subset:
        if table.column(n).type == LogicalType.LIST:
            raise InvalidError(
                f"unique on list passthrough column {n!r} is not supported "
                "(codes are row ids, not value-equal)")
    from ..obs import plan as _plan
    with _plan.node("unique", subset=tuple(subset), keep=keep) as pn:
        if pn:
            pn.set(rows_in=table.row_count)
        if env.world_size > 1:
            table = shuffle_table(table, subset)
        key_datas, key_valids = col_arrays(
            [table.column(n) for n in subset])
        vc = np.asarray(table.valid_counts, np.int32)
        counts = host_array(_unique_count_fn(env.mesh, keep)(
            vc, key_datas, key_valids)).astype(np.int64)
        out_cap = config.pow2ceil(int(counts.max()) if counts.size else 1)
        items = list(table.columns.items())
        datas = tuple(c.data for _, c in items)
        valids = tuple(c.validity for _, c in items)
        from .common import table_lane_spec
        out_d, out_v = _unique_mat_fn(env.mesh, keep, out_cap,
                                      table_lane_spec(
                                          [c for _, c in items]))(
            vc, key_datas, key_valids, datas, valids)
        res = rebuild_like(items, out_d, out_v, counts, env)
        if pn:
            pn.set(rows_out=res.row_count)
        return res


# ---------------------------------------------------------------------------
# union / intersect / subtract (distinct semantics, like the reference)
# ---------------------------------------------------------------------------

def _align_schemas(a: Table, b: Table):
    if a.column_names != b.column_names:
        raise InvalidError(
            f"set op schema mismatch: {a.column_names} vs {b.column_names}")
    cols_a, cols_b = {}, {}
    for n in a.column_names:
        ca, cb = promote_key_pair(a.column(n), b.column(n))
        cols_a[n] = ca
        cols_b[n] = cb
    return (Table(cols_a, a.env, a.valid_counts),
            Table(cols_b, b.env, b.valid_counts))


def _setop_flags_per_shard(vca, vcb, a_datas, a_valids, b_datas, b_valids,
                           op: str):
    cap_a, cap_b = a_datas[0].shape[0], b_datas[0].shape[0]
    mask_a = live_mask(vca, cap_a)
    mask_b = live_mask(vcb, cap_b)
    # operand structures must match across the two tables: emit a null-flag
    # operand for a column when EITHER side is nullable
    need_nf = tuple((av is not None) or (bv is not None)
                    for av, bv in zip(a_valids, b_valids))
    ko_a = pack.key_operands(list(a_datas), list(a_valids), row_mask=mask_a,
                             pad_key=PAD_L, need_null_flags=need_nf)
    ko_b = pack.key_operands(list(b_datas), list(b_valids), row_mask=mask_b,
                             pad_key=PAD_L, need_null_flags=need_nf)
    gids_cat, _ = pack.dense_rank(pack.concat_keyops(ko_a, ko_b))
    side_is_b = jnp.concatenate([jnp.zeros(cap_a, bool), jnp.ones(cap_b, bool)])
    mask_cat = jnp.concatenate([mask_a, mask_b])
    flags = setk.set_op_flags(gids_cat, side_is_b, op, mask_cat)
    return flags


@program_cache()
def _setop_count_fn(mesh: Mesh, op: str):
    def per_shard(vca, vcb, a_datas, a_valids, b_datas, b_valids):
        flags = _setop_flags_per_shard(vca, vcb, a_datas, a_valids, b_datas,
                                       b_valids, op)
        return jnp.sum(flags, dtype=jnp.int32).reshape(1)

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, REP, ROW, ROW, ROW, ROW),
                             out_specs=ROW))


@program_cache()
def _setop_mat_fn(mesh: Mesh, op: str, out_cap: int):
    def per_shard(vca, vcb, a_datas, a_valids, b_datas, b_valids):
        flags = _setop_flags_per_shard(vca, vcb, a_datas, a_valids, b_datas,
                                       b_valids, op)
        idx, _ = sortk.compact_by_flag(flags, out_cap)
        cap_a, cap_b = a_datas[0].shape[0], b_datas[0].shape[0]
        n_cat = cap_a + cap_b
        safe = jnp.clip(idx, 0, max(n_cat - 1, 0))
        out_d, out_v = [], []
        for da, va, db, vb in zip(a_datas, a_valids, b_datas, b_valids):
            cat = jnp.concatenate([da, db])
            out_d.append(cat[safe])
            if va is None and vb is None:
                out_v.append(None)
            else:
                va_ = va if va is not None else jnp.ones(cap_a, bool)
                vb_ = vb if vb is not None else jnp.ones(cap_b, bool)
                out_v.append(jnp.concatenate([va_, vb_])[safe])
        return tuple(out_d), tuple(out_v)

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, REP, ROW, ROW, ROW, ROW),
                             out_specs=(ROW, ROW)))


def set_operation(a: Table, b: Table, op: str,
                  assume_colocated: bool = False) -> Table:
    """union/intersect/subtract with distinct-row semantics (reference
    table.cpp:925-1110).  Distributed path shuffles both tables by full-row
    hash first (:1152-1166).  ``assume_colocated=True`` skips the shuffle
    AND schema alignment (pipelined execution pre-aligns and shuffles the
    resident side once, exec/pipeline.pipelined_set_op).

    Device OOM falls back to the streaming chunked pipeline."""
    from ..core.dtypes import LogicalType
    from .common import run_with_oom_fallback
    for t in (a, b):
        for n in t.column_names:
            if t.column(n).type == LogicalType.LIST:
                raise InvalidError(
                    f"set op on a table with list passthrough column {n!r} "
                    "is not supported (rows are compared by value)")

    def fb(nc):
        from ..exec.pipeline import pipelined_set_op
        return pipelined_set_op(a, b, op, n_chunks=nc)

    from ..obs import plan as _plan
    with _plan.node("set_op", kind=op,
                    colocated=bool(assume_colocated)) as pn:
        if pn:
            pn.set(rows_in=a.row_count + b.row_count)
        res = run_with_oom_fallback(
            lambda: _set_operation_impl(a, b, op, assume_colocated),
            can_fallback=not assume_colocated, fallback=fb, label="set_op",
            env=a.env)
        if pn and type(res) is Table:
            pn.set(rows_out=res.row_count)
        return res


def _set_operation_impl(a: Table, b: Table, op: str,
                        assume_colocated: bool = False) -> Table:
    if op not in ("union", "intersect", "subtract"):
        raise InvalidError(f"unknown set op {op!r}")
    env = check_same_env(a, b)
    if not assume_colocated:
        a, b = _align_schemas(a, b)
    names = a.column_names
    if env.world_size > 1 and not assume_colocated:
        a = shuffle_table(a, names)
        b = shuffle_table(b, names)
    a_datas, a_valids = col_arrays([a.column(n) for n in names])
    b_datas, b_valids = col_arrays([b.column(n) for n in names])
    vca = np.asarray(a.valid_counts, np.int32)
    vcb = np.asarray(b.valid_counts, np.int32)
    counts = host_array(_setop_count_fn(env.mesh, op)(
        vca, vcb, a_datas, a_valids, b_datas, b_valids)).astype(np.int64)
    out_cap = config.pow2ceil(int(counts.max()) if counts.size else 1)
    out_d, out_v = _setop_mat_fn(env.mesh, op, out_cap)(
        vca, vcb, a_datas, a_valids, b_datas, b_valids)
    return rebuild_like([(n, a.column(n)) for n in names], out_d, out_v,
                        counts, env)


# ---------------------------------------------------------------------------
# equals (reference table.cpp:1389 Equals / :1440 DistributedEquals)
# ---------------------------------------------------------------------------

@program_cache()
def _equals_fn(mesh: Mesh, kinds: tuple):
    def per_shard(vc, a_datas, a_valids, b_datas, b_valids):
        cap = a_datas[0].shape[0]
        mask = live_mask(vc, cap)
        ok = jnp.ones(cap, bool)
        for da, va, db, vb, kind in zip(a_datas, a_valids, b_datas, b_valids,
                                        kinds):
            va_ = va if va is not None else jnp.ones(cap, bool)
            vb_ = vb if vb is not None else jnp.ones(cap, bool)
            val_eq = pack.op_eq(da, db, kind)
            ok = ok & (va_ == vb_) & (val_eq | ~va_)
        return jnp.all(ok | ~mask).reshape(1)

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, ROW, ROW, ROW, ROW),
                             out_specs=ROW))


def equals(a: Table, b: Table, ordered: bool = True) -> bool:
    """Table equality.  ordered=False compares as multisets by sorting both
    on all columns first (the reference's unordered Equals)."""
    env = check_same_env(a, b)
    if a.column_names != b.column_names:
        return False
    if a.row_count != b.row_count:
        return False
    if a.row_count == 0:
        return True
    from ..status import CylonTypeError
    try:
        a, b = _align_schemas(a, b)
    except CylonTypeError:
        # no common key type => schemas are genuinely incomparable;
        # any other exception is a real bug and propagates
        return False
    if not ordered:
        from .sort import sort_table
        names = a.column_names
        a = sort_table(a, names)
        b = sort_table(b, names)
    # repartition-to-match (reference RepartitionToMatchOtherTable :1414)
    if not np.array_equal(a.valid_counts, b.valid_counts):
        b = repartition(b, tuple(int(x) for x in a.valid_counts))
    if a.capacity != b.capacity:
        from .repart import repad_table
        common = max(a.capacity, b.capacity)
        a = repad_table(a, common)
        b = repad_table(b, common)
    names = a.column_names
    a_datas, a_valids = col_arrays([a.column(n) for n in names])
    b_datas, b_valids = col_arrays([b.column(n) for n in names])
    kinds = tuple("f" if a.column(n).type in (LogicalType.FLOAT32,
                                              LogicalType.FLOAT64) else "i"
                  for n in names)
    vc = np.asarray(a.valid_counts, np.int32)
    res = _equals_fn(env.mesh, kinds)(vc, a_datas, a_valids, b_datas, b_valids)
    return bool(host_array(res).all())


# ---------------------------------------------------------------------------
# trace-safety declarations (cylon_tpu.analysis.registry) — pure-local
# shard programs; no collective may appear.  docs/trace_safety.md.
# ---------------------------------------------------------------------------

def _trace_unique_count(mesh):
    w = int(mesh.devices.size)
    cap = 1024
    S = jax.ShapeDtypeStruct
    fn = _unwrap(_unique_count_fn(mesh, "first"))
    return jax.make_jaxpr(fn)(S((w,), np.int32), (S((w * cap,), np.int64),),
                              (S((w * cap,), np.bool_),))


from ..analysis.registry import declare_builder, unwrap as _unwrap  # noqa: E402

declare_builder(f"{__name__}._unique_count_fn", _trace_unique_count,
                tags=("setops",))
