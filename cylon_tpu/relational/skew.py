"""Adaptive skew-split plan facade — THE one place split-set construction
and salt assignment happen (lint rule TS115, docs/skew.md).

ROADMAP item 2 / SURVEY §7 hard-part 4: a Zipf-skewed key column under
plain hash partitioning lands each heavy key whole on one rank, bounding
the whole mesh by its hottest chip.  This module builds the remedy as a
deterministic, rank-coherent PLAN:

1. **Detect** (pack time): the sort-splitter sampling machinery
   (:func:`cylon_tpu.relational.common.sample_key_rows` — evenly spaced
   per-shard positions, shard-weighted) feeds the weighted Misra-Gries
   sketch (:mod:`cylon_tpu.obs.sketch`); key-hash classes whose
   estimated share exceeds ``max(SKEW_GLOBAL_FACTOR / W,
   CYLON_TPU_SKEW_SPLIT_SHARE)`` become candidate heavy keys, each named
   by the FULL sampled key tuple (values + validity bits) so every
   later predicate runs in sort-OPERAND space (``pack.key_operands`` +
   ``rows_cmp_splitters``) — equality and order agree bit-for-bit with
   the join sort itself (float canonicalization, null flags and all);
   a hash collision merely leaves the colliding second key on the
   ordinary hash route.

2. **Plan**: each heavy key gets a CONTIGUOUS rank group anchored at its
   hash-home rank (``ops/hashing.partition_of`` — where plain hashing
   would have sent it), fan-out ``ceil(share * W * FANOUT_FACTOR)``
   clamped to [2, W] and to the key's EXACT row count.  The salt is the
   row's within-key arrival index STRIDED over the group — global row
   ``j`` of the key lands on member ``j mod fanout`` — an
   ORDER-PRESERVING sub-partition (each member's rows are a fixed-stride
   subsequence of the key's rows in global (source rank, source
   position) order, so the unsplit position of every row stays
   closed-form), which is what makes the stitched output bit- and
   order-equal to the unsplit hash plan; a random salt would balance
   equally well but scramble the merge order forever.  Strided (not
   contiguous-chunk) assignment also keeps the exchange's
   per-(src,dst) traffic cells uniform: every SOURCE's heavy rows
   spread over the whole group instead of one source's block landing on
   one member, so the padded exchange stays single-round and the comm
   matrix flat (the measured 2× exchange cost of chunked salting).
   Per-member row counts equal ``repart.even_partition_counts`` (the
   first ``n mod fanout`` members take the remainder) — the stitch's
   accounting rides the same host math either way.

3. **Vote**: the canonical plan hash rides the PR 3 consensus wire
   (:func:`cylon_tpu.exec.recovery.skew_plan_consensus`, a
   ``Code.SkewPlan`` vote) so the recovery ladder, checkpoints and
   elastic resume all see ONE plan before any split collective runs.

4. **Stitch** (after the local join): every output row's position in the
   UNSPLIT plan's global row order is computed from host-known plan
   scalars plus K operand comparisons per row, and
   ``repart.place_by_global_pos`` redistributes onto an even
   order-preserving layout — the output is bit-equal and order-equal to
   the unsplit hash plan with BALANCED shards (the unsplit plan would
   have concentrated the heavy key's entire output on its home rank).

The unarmed path (``CYLON_TPU_SKEW_SPLIT=0``, or no key above the
cutoff) adds zero collectives, zero votes and zero extra exchanges —
detection is one pure-local sample program + one host pull, exactly the
pre-existing heavy-key probe.
"""

from __future__ import annotations

import hashlib
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .. import config
from ..core.table import Table
from ..ctx.context import ROW_AXIS
from ..ops import pack
from ..status import ExecutionError
from ..utils.cache import jit, program_cache
from ..utils.host import host_array
from .common import REP, ROW, fits_int32, live_mask

shard_map = jax.shard_map

__all__ = ["SkewPlan", "StitchState", "consume_unstitched", "detect",
           "heavy_counts", "heavy_flag", "finalize_or_none", "adopt",
           "split_exchange", "stitch_join_output", "last_plan",
           "record_plan", "combine_heavy_partials"]

#: thread-local record of the most recently VOTED plan (bench.py's JSON
#: detail and chaos_soak's same-plan-after-recovery assertions read it)
_TLS = threading.local()


def record_plan(plan) -> None:
    _TLS.last = plan


def last_plan():
    """The most recently voted :class:`SkewPlan` on this thread (None
    when the last eligible join ran unsplit)."""
    return getattr(_TLS, "last", None)


class StitchState:
    """The skew route's deferred-merge handle (DeferredTable.op_state):
    the SPLIT-layout join output plus everything the stitch needs to
    rebuild the unsplit plan's global row order on demand.

    The stitch is a full extra pass over the output (position programs +
    one order-preserving exchange + per-dest reorder) — but row ORDER
    and PLACEMENT are unobservable through an aggregation, so a groupby
    consumer takes ``pre`` directly (:func:`consume_unstitched`) and the
    merge exchange never runs — the PR 2 deferred-consumption discipline
    applied to the stitch.  Any other access (to_pandas, sort, a second
    join, ...) materializes through the stitch thunk and sees the exact
    bit- and order-equal table (docs/skew.md)."""

    __slots__ = ("pre", "plan", "how", "un_counts", "key_out_names")

    def __init__(self, pre: Table, plan, how: str, un_counts,
                 key_out_names):
        self.pre = pre
        self.plan = plan
        self.how = how
        self.un_counts = un_counts
        self.key_out_names = tuple(key_out_names)


def consume_unstitched(table, include_deferred: bool = False):
    """Hand an order-insensitive consumer (relational/groupby.py) the
    PRE-stitch table when ``table`` is a stitch-deferred skew join:
    aggregation output is a function of the row MULTISET only (key
    placement is re-derived by the groupby's own combine shuffle), so
    skipping the stitch changes nothing observable while saving a full
    pass over the join output.  Returns ``table`` unchanged otherwise.

    ``include_deferred=True`` (called AFTER the fused pushdown declined
    — relational/groupby._groupby_aggregate_impl) additionally handles a
    still-deferred skew JOIN (fused.JoinState with a plan): the state's
    ``pre_thunk`` materializes the SPLIT-layout output without the
    stitch, so a groupby the fused kernel cannot serve (min/max/
    quantile/...) still skips the merge exchange."""
    st = getattr(table, "op_state", None)
    if isinstance(st, StitchState):
        from ..obs import plan as _plan
        from ..utils import timing
        _plan.annotate(skew_stitch_elided=True)
        timing.bump("skew.stitch_elided")
        return st.pre
    if include_deferred and not getattr(table, "materialized", True):
        pre_thunk = getattr(st, "pre_thunk", None)
        if getattr(st, "skew_plan", None) is not None \
                and pre_thunk is not None:
            from ..obs import plan as _plan
            from ..utils import timing
            _plan.annotate(skew_stitch_elided=True)
            timing.bump("skew.stitch_elided")
            return pre_thunk()
    return table


# ---------------------------------------------------------------------------
# the plan object
# ---------------------------------------------------------------------------

class SkewPlan:
    """The split decision for one join: K heavy key tuples with their
    contiguous rank groups and order-preserving chunk (salt) bounds.
    Built in two steps: :func:`detect` fills the sampled estimate,
    :meth:`finalize` replaces it with EXACT counts (and drops keys the
    replication guard rejects) before the plan hash is voted."""

    __slots__ = ("world", "key_names", "values", "valids", "hashes",
                 "shares", "home", "start", "fanout", "n_probe", "n_build",
                 "chunk", "src_off", "lt", "_hash")

    def __init__(self, world: int, key_names: tuple, values: list,
                 valids: list, hashes: np.ndarray, shares: np.ndarray,
                 home: np.ndarray, fanout: np.ndarray):
        self.world = int(world)
        self.key_names = tuple(key_names)
        self.values = values          # per key column: (K,) value array
        self.valids = valids          # per key column: (K,) bool array
        self.hashes = hashes          # (K,) uint32 routing hashes
        self.shares = shares          # (K,) estimated probe share
        self.home = home              # (K,) int32 hash-home rank
        self.start = home.copy()      # contiguous group anchored at home
        self.fanout = fanout          # (K,) int32 (estimate until finalize)
        self.n_probe = None           # (K,) exact probe rows (finalize)
        self.n_build = None           # (K,) exact build rows (finalize)
        self.chunk = None             # (K, W) per-member chunk rows
        self.src_off = None           # (W, K) within-key source offsets
        self.lt = None                # (K, K) operand order: lt[i,j]=ti<tj
        self._hash = None

    def __len__(self) -> int:
        return len(self.hashes)

    def _take(self, keep: np.ndarray) -> None:
        self.values = [v[keep] for v in self.values]
        self.valids = [v[keep] for v in self.valids]
        for name in ("hashes", "shares", "home", "start", "fanout"):
            setattr(self, name, getattr(self, name)[keep])

    def finalize(self, probe_wk: np.ndarray, ltmat: np.ndarray,
                 build_wk: np.ndarray, build_total: int) -> bool:
        """Swap the sampled estimate for EXACT per-source counts, clamp
        fan-outs, apply the per-key replication guard, and derive the
        salt (chunk) bounds.  Returns False when nothing is left to
        split.  Pure host arithmetic on replicated sidecars — identical
        on every rank by construction."""
        from .repart import even_partition_counts
        w = self.world
        n_probe = probe_wk.sum(axis=0).astype(np.int64)
        n_build = build_wk.sum(axis=0).astype(np.int64)
        # replication guard: duplicate-broadcasting a key whose BUILD
        # side is itself huge recreates the blow-up the split avoids
        guard = (n_build > config.SKEW_GUARD_ROWS) \
            & (n_build * w > config.SKEW_GUARD_RATIO * max(build_total, 1))
        keep = (n_probe > 0) & ~guard
        if not keep.any():
            return False
        self._take(keep)
        probe_wk = probe_wk[:, keep]
        ltmat = ltmat[keep][:, keep]
        n_probe, n_build = n_probe[keep], n_build[keep]
        self.n_probe, self.n_build, self.lt = n_probe, n_build, ltmat
        self.fanout = np.minimum(
            np.minimum(self.fanout.astype(np.int64), n_probe),
            w).astype(np.int32)
        self.fanout = np.maximum(self.fanout, 1).astype(np.int32)
        k = len(self.hashes)
        self.chunk = np.zeros((k, w), np.int64)
        for i in range(k):
            f = int(self.fanout[i])
            self.chunk[i, :f] = even_partition_counts(int(n_probe[i]), f)
        self.src_off = np.concatenate(
            [np.zeros((1, k), np.int64),
             np.cumsum(probe_wk, axis=0)[:-1].astype(np.int64)])
        self._hash = None
        return True

    # -- identity ---------------------------------------------------------
    def plan_hash(self) -> int:
        """Canonical 64-bit plan identity: every field that shapes the
        split's collective sequence feeds a sha256.  Deterministic given
        the (allgathered) detection inputs, so a recovery-ladder retry
        re-votes the identical hash — the chaos ``--skew`` contract."""
        if self._hash is None:
            h = hashlib.sha256()
            h.update(repr((self.world, self.key_names,
                           tuple(str(v.dtype) for v in self.values))
                          ).encode())
            for v in self.values + self.valids:
                h.update(np.ascontiguousarray(v).tobytes())
            for a in (self.hashes, self.home, self.start, self.fanout,
                      self.n_probe, self.n_build, self.chunk):
                h.update(np.ascontiguousarray(a).tobytes())
            self._hash = int.from_bytes(h.digest()[:8], "big")
        return self._hash

    def summary(self) -> dict:
        """The JSON-friendly decision record (bench detail, EXPLAIN)."""
        return {
            "keys": int(len(self.hashes)),
            "fanout": [int(f) for f in self.fanout],
            "home": [int(d) for d in self.home],
            "share_est": [round(float(s), 4) for s in self.shares],
            "rows_probe": [int(n) for n in self.n_probe]
            if self.n_probe is not None else None,
            "rows_build": [int(n) for n in self.n_build]
            if self.n_build is not None else None,
            "plan_hash": format(self.plan_hash(), "016x"),
        }

    # -- operand-space statics -------------------------------------------
    def operand_statics(self, cols) -> tuple:
        """(need_nf, narrow) per key column for operand comparisons
        between ``cols``' rows and this plan's tuples — null flags
        whenever either side can hold nulls, narrow lanes only when BOTH
        the column's host-known bounds AND this plan's tuple values fit
        int32.  The tuples are drawn from the PROBE table, but ``cols``
        may be the BUILD side (or the join output): a build column whose
        bounds fit int32 compared against a wide probe tuple must stay
        on the (hi, lo) pair, or the tuple's truncation aliases it onto
        an unrelated narrow key (the cross-table rule of
        ``common.narrow32_flags``, applied one side at a time)."""
        need_nf = tuple((c.validity is not None) or bool((~tv).any())
                        for c, tv in zip(cols, self.valids))
        narrow = tuple(fits_int32(c) and _tuple_fits_i32(v, tv)
                       for c, v, tv in zip(cols, self.values, self.valids))
        return need_nf, narrow

    def tuple_args(self) -> tuple:
        """The replicated device-constant inputs naming the K tuples."""
        return tuple(self.values) + tuple(self.valids)


def _tuple_fits_i32(v: np.ndarray, tv: np.ndarray) -> bool:
    """Host-known: every VALID entry of this 64-bit integer tuple-value
    array fits int32 (the per-tuple half of :meth:`SkewPlan.
    operand_statics`' narrow-lane rule; null slots may hold garbage)."""
    if v.dtype.itemsize != 8 or v.dtype.kind not in ("i", "u"):
        return False
    live = v[tv]
    if live.size == 0:
        return True
    return int(live.min()) >= -(1 << 31) \
        and int(live.max()) <= (1 << 31) - 1


def _cmp_args(table: Table, key_names) -> tuple:
    cols = [table.column(n) for n in key_names]
    cap = cols[0].data.shape[0]
    datas = tuple(c.data for c in cols)
    valids = tuple(c.validity if c.validity is not None
                   else np.ones(cap, bool) for c in cols)
    return cols, datas, valids


def _tuple_ops(tup, nkeys: int, need_nf: tuple, narrow: tuple):
    """KeyOps of the K heavy tuples from the replicated constants."""
    tdatas = list(tup[:nkeys])
    tvalids = list(tup[nkeys:])
    return pack.key_operands(tdatas, tvalids, need_null_flags=need_nf,
                             narrow32=narrow)


def _row_ops(datas, valids, need_nf: tuple, narrow: tuple):
    return pack.key_operands(list(datas), list(valids),
                             need_null_flags=need_nf, narrow32=narrow)


# ---------------------------------------------------------------------------
# detection — MG sketch over the splitter sample
# ---------------------------------------------------------------------------

def detect(probe: Table, key_names, env) -> SkewPlan | None:
    """Pack-time heavy-hitter detection on the (promoted) probe side.
    Returns an un-finalized :class:`SkewPlan` or None.  One pure-local
    sample program + one (allgathered) host pull; rank-uniform by
    construction."""
    from ..obs.sketch import MisraGries
    from ..ops.hashing import partition_of
    from .common import sample_key_rows

    # every eligible join's decision sequence starts here: clear the
    # thread-local record so last_plan() never reports a PREVIOUS join's
    # plan when this one runs unsplit (adopt() re-records on a vote)
    record_plan(None)
    w = env.world_size
    if not config.SKEW_SPLIT or w <= 1:
        return None
    total = int(probe.valid_counts.sum())
    if total < w * 64:   # too small to be worth a split
        return None
    sampled = sample_key_rows(probe, list(key_names))
    if sampled is None:
        return None
    values, valids, hashes, weights, _total = sampled
    mg = MisraGries(k=max(4 * config.SKEW_MAX_KEYS, 8))
    mg.update(hashes, weights)
    thresh = max(config.SKEW_GLOBAL_FACTOR / w, config.SKEW_SPLIT_SHARE)
    heavy = [(hv, sh) for hv, sh, _err in mg.shares() if sh > thresh]
    if not heavy:
        return None
    heavy = heavy[:config.SKEW_MAX_KEYS]
    idx, shares = [], []
    for hv, sh in heavy:
        pos = np.nonzero(hashes == hv)[0]
        if pos.size == 0:   # MG value decayed out of the sample: skip
            continue
        idx.append(int(pos[0]))
        shares.append(float(sh))
    if not idx:
        return None
    idx = np.asarray(idx, np.int64)
    shares = np.asarray(shares, np.float64)
    hv = hashes[idx].astype(np.uint32)
    home = np.asarray([partition_of(int(h), w) for h in hv], np.int32)
    fanout = np.clip(np.ceil(shares * w * config.SKEW_FANOUT_FACTOR), 2,
                     w).astype(np.int32)
    return SkewPlan(w, tuple(key_names),
                    [np.ascontiguousarray(v[idx]) for v in values],
                    [np.ascontiguousarray(v[idx]) for v in valids],
                    hv, shares, home, fanout)


def adopt(plan: SkewPlan, env) -> None:
    """Vote the finalized plan's canonical hash over the PR 3 consensus
    wire (:func:`cylon_tpu.exec.recovery.skew_plan_consensus`,
    ``Code.SkewPlan``) and record it for the bench/chaos assertions.
    Must run BEFORE the split's first collective is dispatched — a rank
    whose detection inputs diverged raises typed here instead of
    entering a different exchange plan alone."""
    from ..exec.recovery import skew_plan_consensus
    from ..obs import metrics as _metrics
    from ..utils import timing
    skew_plan_consensus(env.mesh, plan.plan_hash())
    record_plan(plan)
    timing.bump("join.skew_split")
    _metrics.counter("skew_split_joins").inc()
    _metrics.counter("skew_split_keys").inc(len(plan))


def split_exchange(probe: Table, probe_on, build: Table, build_on,
                   plan: SkewPlan):
    """Run the split's exchanges per the VOTED plan (docs/skew.md):

    * **probe**: one exchange with the salted order-preserving targets —
      light rows hash to their home shard exactly like the unsplit plan,
      each heavy key's rows land as fixed-stride global-order
      subsequences on its rank group
      (:func:`parallel.shuffle.skew_split_targets`);
    * **build**: light rows hash-shuffle; heavy rows duplicate-broadcast
      (allgather — the existing broadcast-join transport) then filter to
      the ranks serving the key's group, appended AFTER the light block
      so every shard's per-key row order stays the global (src, pos)
      order the unsplit hash exchange would have delivered — the
      bit-equality contract's build half.

    Returns ``(probe_out, build_out)``."""
    from ..parallel import shuffle as shf
    from ..parallel.collectives import allgather_table
    from .repart import (concat_tables, exchange_by_targets, filter_table,
                         shuffle_table)

    env = probe.env
    cols, datas, valids = _cmp_args(probe, probe_on)
    need_nf, narrow = plan.operand_statics(cols)
    tgt = shf.skew_split_targets(
        env.mesh, datas, valids, probe.valid_counts, len(plan), need_nf,
        narrow, plan.tuple_args(), plan.src_off, plan.fanout, plan.start)
    counts = shf.count_targets(env.mesh, tgt)
    probe_out = exchange_by_targets(probe, tgt, counts)

    flag = heavy_flag(build, build_on, plan)
    build_light = filter_table(build, ~flag)
    build_heavy = filter_table(build, flag)
    bh_all = allgather_table(build_heavy)
    keep = heavy_flag(bh_all, build_on, plan,
                      member=group_member_mask(plan))
    bh_mine = filter_table(bh_all, keep)
    build_out = concat_tables([shuffle_table(build_light, build_on),
                               bh_mine])
    return probe_out, build_out


def finalize_or_none(plan: SkewPlan, probe: Table, probe_on,
                     build: Table, build_on) -> SkewPlan | None:
    """Exact-count finalization: per-source probe counts + operand order
    matrix + build counts, then :meth:`SkewPlan.finalize`.  Returns the
    finalized plan or None (nothing worth splitting)."""
    probe_wk, ltmat = heavy_counts(probe, probe_on, plan, with_lt=True)
    build_wk, _ = heavy_counts(build, build_on, plan)
    if not plan.finalize(probe_wk, ltmat, build_wk,
                         int(build.valid_counts.sum())):
        return None
    return plan


# ---------------------------------------------------------------------------
# exact per-source counts + operand order (one pure-local program)
# ---------------------------------------------------------------------------

@program_cache()
def _heavy_count_fn(mesh: Mesh, k: int, nkeys: int, need_nf: tuple,
                    narrow: tuple):
    def per_shard(vc, *args):
        datas = args[:nkeys]
        valids = args[nkeys:2 * nkeys]
        tup = args[2 * nkeys:]
        cap = datas[0].shape[0]
        mask = live_mask(vc, cap)
        ko_t = _tuple_ops(tup, nkeys, need_nf, narrow)
        ko_r = _row_ops(datas, valids, need_nf, narrow)
        _gt, eq = pack.rows_cmp_splitters(ko_r, ko_t.ops)
        counts = jnp.sum(eq & mask[:, None], axis=0,
                         dtype=jnp.int32).reshape(1, k)
        # operand order among the tuples themselves: lt[i, j] = t_i < t_j
        gtt, _eqt = pack.rows_cmp_splitters(ko_t, ko_t.ops)
        return counts, gtt.T

    specs = (REP,) + (ROW,) * (2 * nkeys) + (REP,) * (2 * nkeys)
    return jit(shard_map(per_shard, mesh=mesh, in_specs=specs,
                             out_specs=(ROW, REP)))


def heavy_counts(table: Table, key_names, plan: SkewPlan,
                 with_lt: bool = False):
    """(W, K) exact per-source row counts of each heavy tuple in
    ``table``, plus (with_lt) the (K, K) operand-order matrix."""
    cols, datas, valids = _cmp_args(table, key_names)
    need_nf, narrow = plan.operand_statics(cols)
    fn = _heavy_count_fn(table.env.mesh, len(plan), len(cols), need_nf,
                         narrow)
    counts_d, lt_d = fn(np.asarray(table.valid_counts, np.int32),
                        *datas, *valids, *plan.tuple_args())
    counts = host_array(counts_d).reshape(table.env.world_size, len(plan))
    return counts.astype(np.int64), (host_array(lt_d) if with_lt else None)


# ---------------------------------------------------------------------------
# membership flags (build-side split + group-scoped broadcast filter)
# ---------------------------------------------------------------------------

@program_cache()
def _heavy_member_flag_fn(mesh: Mesh, k: int, nkeys: int, need_nf: tuple,
                          narrow: tuple):
    """Per-row bool: the row's key equals SOME heavy tuple whose (K, W)
    member mask covers THIS rank.  All-ones mask ⇒ the plain split flag;
    the group mask ⇒ the duplicate-broadcast's group-scoped filter."""

    def per_shard(vc, member, *args):
        datas = args[:nkeys]
        valids = args[nkeys:2 * nkeys]
        tup = args[2 * nkeys:]
        cap = datas[0].shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        mask = live_mask(vc, cap)
        ko_t = _tuple_ops(tup, nkeys, need_nf, narrow)
        ko_r = _row_ops(datas, valids, need_nf, narrow)
        _gt, eq = pack.rows_cmp_splitters(ko_r, ko_t.ops)
        return jnp.any(eq & member[:, my][None, :], axis=1) & mask

    specs = (REP, REP) + (ROW,) * (2 * nkeys) + (REP,) * (2 * nkeys)
    return jit(shard_map(per_shard, mesh=mesh, in_specs=specs,
                             out_specs=ROW))


def heavy_flag(table: Table, key_names, plan: SkewPlan, member=None):
    """Device bool flags: row's key is heavy (``member=None``) or heavy
    AND this rank belongs to the key's group (``member`` a (K, W) bool
    mask — :func:`group_member_mask`)."""
    cols, datas, valids = _cmp_args(table, key_names)
    need_nf, narrow = plan.operand_statics(cols)
    if member is None:
        member = np.ones((len(plan), plan.world), bool)
    fn = _heavy_member_flag_fn(table.env.mesh, len(plan), len(cols),
                               need_nf, narrow)
    return fn(np.asarray(table.valid_counts, np.int32), member,
              *datas, *valids, *plan.tuple_args())


def group_member_mask(plan: SkewPlan) -> np.ndarray:
    """(K, W) bool: rank w serves key k's group (contiguous mod W from
    the key's home anchor)."""
    k, w = len(plan), plan.world
    m = np.zeros((k, w), bool)
    for i in range(k):
        for j in range(int(plan.fanout[i])):
            m[i, (int(plan.start[i]) + j) % w] = True
    return m


# ---------------------------------------------------------------------------
# fused-pushdown heavy-partial combine (relational/fused.py)
# ---------------------------------------------------------------------------

@program_cache()
def _heavy_partial_sum_fn(mesh: Mesh, k: int, nkeys: int, need_nf: tuple,
                          narrow: tuple, nvals: int):
    """(W, K) per-source partial values of each heavy key's GROUP-SPACE
    result row (one matching row per member shard, zeros elsewhere) —
    the gather half of :func:`combine_heavy_partials`.  Pure-local."""

    def per_shard(vc, *args):
        datas = args[:nkeys]
        valids = args[nkeys:2 * nkeys]
        tup = args[2 * nkeys:4 * nkeys]
        vals = args[4 * nkeys:]
        cap = datas[0].shape[0]
        mask = live_mask(vc, cap)
        ko_t = _tuple_ops(tup, nkeys, need_nf, narrow)
        ko_r = _row_ops(datas, valids, need_nf, narrow)
        _gt, eq = pack.rows_cmp_splitters(ko_r, ko_t.ops)
        eq = eq & mask[:, None]
        return tuple(
            jnp.sum(jnp.where(eq, v[:, None], jnp.zeros((), v.dtype)),
                    axis=0).reshape(1, k)
            for v in vals)

    specs = (REP,) + (ROW,) * (2 * nkeys) + (REP,) * (2 * nkeys) \
        + (ROW,) * nvals
    return jit(shard_map(per_shard, mesh=mesh, in_specs=specs,
                             out_specs=(ROW,) * nvals))


@program_cache()
def _patch_heavy_fn(mesh: Mesh, k: int, nkeys: int, need_nf: tuple,
                    narrow: tuple, nvals: int):
    """Patch half of :func:`combine_heavy_partials`: heavy rows on the
    key's HOME rank take the combined value; heavy rows on the other
    group members are flagged for dropping.  Light rows pass through.
    Pure-local."""

    def per_shard(vc, home, *args):
        datas = args[:nkeys]
        valids = args[nkeys:2 * nkeys]
        tup = args[2 * nkeys:4 * nkeys]
        vals = args[4 * nkeys:4 * nkeys + nvals]
        combined = args[4 * nkeys + nvals:]
        cap = datas[0].shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        mask = live_mask(vc, cap)
        ko_t = _tuple_ops(tup, nkeys, need_nf, narrow)
        ko_r = _row_ops(datas, valids, need_nf, narrow)
        _gt, eq = pack.rows_cmp_splitters(ko_r, ko_t.ops)
        eq = eq & mask[:, None]
        heavy = jnp.any(eq, axis=1)
        kidx = jnp.argmax(eq, axis=1).astype(jnp.int32)
        is_home = heavy & (home[kidx] == my)
        keep = mask & (~heavy | is_home)
        outs = tuple(jnp.where(is_home, c[kidx], v)
                     for v, c in zip(vals, combined))
        return outs + (keep,)

    specs = (REP, REP) + (ROW,) * (2 * nkeys) + (REP,) * (2 * nkeys) \
        + (ROW,) * nvals + (REP,) * nvals
    return jit(shard_map(per_shard, mesh=mesh, in_specs=specs,
                             out_specs=(ROW,) * (nvals + 1)))


def combine_heavy_partials(out: Table, by, res_names, plan: SkewPlan):
    """Merge a fused join→groupby pushdown's heavy-key PARTIAL rows into
    the unsplit plan's single row per key (relational/fused.py).

    Under a skew plan each heavy key's probe rows span a rank group, so
    the fused kernel's group-space result holds one partial row per
    member — and for the pushdown-eligible-under-skew ops (sum/count/
    sumsq, whose finalized value is ADDITIVE in the probe chunks:
    ``S_chunk·R`` over members sums to ``S_g·R``) the combine is: sum
    each heavy key's member rows, write the total onto the key's HOME
    rank's row, drop the other members' rows.  The surviving per-shard
    group sets, row order and values are then exactly the unsplit fused
    plan's (the home rank is where plain hashing co-located the key),
    which is the skew route's bit-equality contract applied to the
    aggregated output — exact for integer accumulators; a FLOAT sum
    re-associates (per-chunk partials folded in rank order vs one
    shard's single pass) and may differ from the unsplit run in
    low-order bits, deterministically (docs/skew.md "Scope of the
    aggregated-output equality").  Two tiny pure-local programs +
    one (W, K)-sidecar host pull; the combined constants are identical
    on every rank because the pull allgathers."""
    from ..core.column import Column
    from ..obs import plan as _plan
    from ..utils import timing
    from .repart import filter_table

    env = out.env
    cols, datas, valids = _cmp_args(out, by)
    need_nf, narrow = plan.operand_statics(cols)
    vals = [out.column(n) for n in res_names]
    vdatas = tuple(c.data for c in vals)
    vc32 = np.asarray(out.valid_counts, np.int32)
    k, nk, w = len(plan), len(cols), plan.world
    with timing.region("skew.partial_combine"):
        parts = _heavy_partial_sum_fn(env.mesh, k, nk, need_nf, narrow,
                                      len(vals))(
            vc32, *datas, *valids, *plan.tuple_args(), *vdatas)
        # rank-order host fold — deterministic and rank-uniform
        combined = [np.ascontiguousarray(
            host_array(p).reshape(w, k).sum(axis=0)) for p in parts]
        outs = _patch_heavy_fn(env.mesh, k, nk, need_nf, narrow,
                               len(vals))(
            vc32, plan.home.astype(np.int32), *datas, *valids,
            *plan.tuple_args(), *vdatas, *combined)
        new_datas, keep = outs[:-1], outs[-1]
        newcols = dict(out.columns)
        for n, d in zip(res_names, new_datas):
            c = out.columns[n]
            # bounds dropped: the combined totals may exceed the partial
            # rows' recorded range
            newcols[n] = Column(d, c.type, c.validity, c.dictionary)
        patched = Table(newcols, env,
                        np.asarray(out.valid_counts, np.int64))
        res = filter_table(patched, keep)
    res.grouped_by = tuple(by)
    _plan.annotate(skew_partials_combined=k)
    timing.bump("skew.partial_combine")
    return res


# ---------------------------------------------------------------------------
# stitch: O-position of every output row in the UNSPLIT plan's order
# ---------------------------------------------------------------------------

@program_cache()
def _out_ltcount_fn(mesh: Mesh, k: int, nkeys: int, need_nf: tuple,
                    narrow: tuple):
    """(W, K) counts of MAIN-zone output rows whose key sorts strictly
    after tuple k ... transposed perspective: rows with t_k < rowkey."""

    def per_shard(vc, main, *args):
        datas = args[:nkeys]
        valids = args[nkeys:2 * nkeys]
        tup = args[2 * nkeys:]
        cap = datas[0].shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        zone_a = jnp.arange(cap, dtype=jnp.int32) < main[my]
        ko_t = _tuple_ops(tup, nkeys, need_nf, narrow)
        ko_r = _row_ops(datas, valids, need_nf, narrow)
        gt, _eq = pack.rows_cmp_splitters(ko_r, ko_t.ops)
        return jnp.sum(gt & zone_a[:, None], axis=0,
                       dtype=jnp.int32).reshape(1, k)

    specs = (REP, REP) + (ROW,) * (2 * nkeys) + (REP,) * (2 * nkeys)
    return jit(shard_map(per_shard, mesh=mesh, in_specs=specs,
                             out_specs=ROW))


@program_cache()
def _stitch_pos_fn(mesh: Mesh, k: int, nkeys: int, need_nf: tuple,
                   narrow: tuple):
    """Per-row UNSPLIT-plan global position (int64) of the split join's
    output rows — the merge half of the skew route's bit/order-equality
    contract (module docstring, docs/skew.md):

    * light main row at shard r, slot p:
        ``segoff[r] + p + Σ_{t_j < key} coefA[r, j]``
      (coefA removes the heavy slices sorting before it and inserts the
      full heavy blocks HOMED at r that sort before it);
    * heavy row of key j: the member holds probe rows ``m, m+f, m+2f...``
      of the key (strided salt), each contributing ``per_row`` output
      rows, so output row ``within_run`` (probe ordinal ``i = within //
      per_row``, build ordinal ``b = within mod per_row``) sits at
        ``coefH[r, j] + i · (fanout_j · per_row_j) + b``
      (coefH = the key's global block base + this member's salt ordinal
      times ``per_row``; within_run from one run-boundary scan);
    * appended unmatched-right row (outer zone B):
        ``segoff[r] + seg_a[r] + (p - main[r])``.

    Padding slots get the ``total`` sentinel (they sort last and are
    dropped by the placement's valid counts)."""

    def per_shard(vc, main, segoff, seg_a, coef_a, coef_h, per_row, fan,
                  total, *args):
        datas = args[:nkeys]
        valids = args[nkeys:2 * nkeys]
        tup = args[2 * nkeys:]
        cap = datas[0].shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        p32 = jnp.arange(cap, dtype=jnp.int32)
        # born-wide int64 twin for position arithmetic (JX203): global
        # output positions legitimately exceed int32 at target scale
        p64 = jnp.arange(cap, dtype=jnp.int64)
        live = p32 < vc[my]
        zone_b = live & (p32 >= main[my])
        ko_t = _tuple_ops(tup, nkeys, need_nf, narrow)
        ko_r = _row_ops(datas, valids, need_nf, narrow)
        gt, eq = pack.rows_cmp_splitters(ko_r, ko_t.ops)
        heavy = jnp.any(eq, axis=1) & live & ~zone_b
        kidx = jnp.argmax(eq, axis=1).astype(jnp.int32)
        # run boundaries over the shard's (key-sorted) main zone: the
        # heavy key's rows form one contiguous run; within_run is the
        # row's offset inside it
        neq = jnp.zeros(cap, bool)
        for op, kind in zip(ko_r.ops, ko_r.kinds):
            d = pack.op_neq(op[1:], op[:-1], kind)
            neq = neq | jnp.concatenate([jnp.ones(1, bool), d])
        run_start = jax.lax.cummax(jnp.where(neq, p64, jnp.int64(0)))
        within = p64 - run_start
        # light: p + Σ_j [t_j < key] * coefA[my, j]
        corr = jnp.sum(jnp.where(gt, coef_a[my][None, :],
                                 jnp.int64(0)), axis=1)
        pos_light = segoff[my] + p64 + corr
        # pr=1 guard: a key with zero build rows emits no heavy output
        # rows at all (kidx then points at it only from non-heavy lanes
        # whose pos_heavy is discarded), but the division must not trap
        pr = jnp.maximum(per_row[kidx], jnp.int64(1))
        i = within // pr
        b = within - i * pr
        pos_heavy = coef_h[my, kidx] + i * (fan[kidx] * pr) + b
        pos_b = segoff[my] + seg_a[my] + (p64 - main[my])
        pos = jnp.where(zone_b, pos_b,
                        jnp.where(heavy, pos_heavy, pos_light))
        return jnp.where(live, pos, total)

    specs = (REP,) * 9 + (ROW,) * (2 * nkeys) + (REP,) * (2 * nkeys)
    return jit(shard_map(per_shard, mesh=mesh, in_specs=specs,
                             out_specs=ROW))


def stitch_join_output(out: Table, key_out_names, plan: SkewPlan,
                       how: str, un_counts: np.ndarray | None) -> Table:
    """Merge the split join's output back into the UNSPLIT hash plan's
    global row order (bit- and order-equal), redistributed onto an even
    order-preserving layout via ``repart.place_by_global_pos``.

    ``key_out_names``: the output columns holding the PROBE side's key
    values.  ``un_counts``: per-shard appended unmatched-right counts
    (outer joins; None ⇒ zeros)."""
    from ..utils import timing
    from .repart import place_by_global_pos

    env = out.env
    w, k = plan.world, len(plan)
    out_counts = np.asarray(out.valid_counts, np.int64)
    total = int(out_counts.sum())
    un = np.zeros(w, np.int64) if un_counts is None \
        else np.asarray(un_counts, np.int64)
    main = out_counts - un

    per_row = plan.n_build if how == "inner" \
        else np.maximum(plan.n_build, 1)
    out_k = (plan.n_probe * per_row).astype(np.int64)      # (K,) blocks
    # slice_size[r, j]: heavy output rows of key j at member shard r
    ordinal = (np.arange(w)[:, None] - plan.start[None, :]) % w   # (W, K)
    in_group = ordinal < plan.fanout[None, :]
    chunk_rows = np.where(
        in_group, plan.chunk[np.arange(k)[None, :],
                             np.clip(ordinal, 0, w - 1)], 0)
    slice_size = chunk_rows * per_row[None, :]
    # strided salt: member ordinal m holds probe rows m, m+f, m+2f... of
    # the key, so its FIRST output row sits at block offset m * per_row
    # (the stride itself is applied per row in _stitch_pos_fn)
    slice_off = np.where(in_group, np.clip(ordinal, 0, w - 1), 0) \
        * per_row[None, :]

    light_main = main - slice_size.sum(axis=1)
    home_mat = (plan.home[None, :] == np.arange(w)[:, None])      # (W, K)
    seg = light_main + home_mat @ out_k + un                      # (W,)
    if int(seg.sum()) != total:
        raise ExecutionError(
            f"skew stitch accounting diverged: unsplit segments sum to "
            f"{int(seg.sum())} rows but the split output holds {total} — "
            "plan counts and join output disagree")
    segoff = np.concatenate([[0], np.cumsum(seg)[:-1]]).astype(np.int64)

    cols = [out.column(n) for n in key_out_names]
    need_nf, narrow = plan.operand_statics(cols)
    cap = cols[0].data.shape[0]
    datas = tuple(c.data for c in cols)
    valids = tuple(c.validity if c.validity is not None
                   else np.ones(cap, bool) for c in cols)
    vc32 = np.asarray(out_counts, np.int32)
    main32 = np.asarray(main, np.int32)
    with timing.region("skew.stitch_count"):
        jlt = _out_ltcount_fn(env.mesh, k, len(cols), need_nf, narrow)(
            vc32, main32, *datas, *valids, *plan.tuple_args())
        jlt = host_array(jlt).reshape(w, k).astype(np.int64)
    # light rows at the HOME shard sorting after key j's tuple (exclude
    # the other heavy keys' slices the joint count included: key j' at
    # shard d counts against tuple k iff t_k < t_j', i.e. lt[k, j'])
    light_lt = jlt - slice_size @ plan.lt.T.astype(np.int64)
    # the key's global block base in the UNSPLIT plan: its home segment's
    # offset + the light rows sorting BEFORE it there (light_main minus
    # the after-count — no light key ever equals a heavy tuple) + the
    # full blocks of heavy keys ALSO homed there that sort before it
    light_before = light_main[plan.home] - light_lt[plan.home,
                                                    np.arange(k)]
    block_base = (segoff[plan.home] + light_before
                  + ((plan.lt & (plan.home[:, None] == plan.home[None, :]))
                     .T @ out_k))
    coef_a = (-slice_size + home_mat * out_k[None, :]).astype(np.int64)
    coef_h = (block_base[None, :] + slice_off).astype(np.int64)

    with timing.region("skew.stitch_pos"):
        pos = _stitch_pos_fn(env.mesh, k, len(cols), need_nf, narrow)(
            vc32, main32, segoff, seg - un, coef_a, coef_h,
            per_row.astype(np.int64), plan.fanout.astype(np.int64),
            np.int64(total), *datas, *valids, *plan.tuple_args())
    with timing.region("skew.stitch_place"):
        stitched = place_by_global_pos(out, pos, total)
    from ..exec import integrity as _integrity
    if _integrity.armed():
        # armed audit (exec/integrity facade): the stitched table's
        # order-invariant fingerprint is voted rank-coherently — a
        # corrupted or mis-placed stitch surfaces typed at this stage
        # boundary instead of as a silently reordered answer downstream
        _integrity.audit_table(stitched, site="skew.stitch",
                               phase="post_stitch")
    return stitched


# ---------------------------------------------------------------------------
# trace-safety declarations: pure-local shard programs, no collective
# (the split's exchanges ride parallel/shuffle.py).  docs/trace_safety.md.
# ---------------------------------------------------------------------------

def _decl(mesh, k=2):
    w = int(mesh.devices.size)
    cap, S = 1024, jax.ShapeDtypeStruct
    vc = S((w,), np.int32)
    keys = (S((w * cap,), np.int64),)
    valids = (S((w * cap,), np.bool_),)
    tup = (S((k,), np.int64), S((k,), np.bool_))
    return w, cap, S, vc, keys, valids, tup


def _trace_heavy_count(mesh):
    w, cap, S, vc, keys, valids, tup = _decl(mesh)
    fn = _unwrap(_heavy_count_fn(mesh, 2, 1, (True,), (False,)))
    return jax.make_jaxpr(fn)(vc, *keys, *valids, *tup)


def _trace_member_flag(mesh):
    w, cap, S, vc, keys, valids, tup = _decl(mesh)
    fn = _unwrap(_heavy_member_flag_fn(mesh, 2, 1, (True,), (False,)))
    return jax.make_jaxpr(fn)(vc, S((2, w), np.bool_), *keys, *valids,
                              *tup)


def _trace_heavy_partial_sum(mesh):
    w, cap, S, vc, keys, valids, tup = _decl(mesh)
    fn = _unwrap(_heavy_partial_sum_fn(mesh, 2, 1, (True,), (False,), 2))
    return jax.make_jaxpr(fn)(vc, *keys, *valids, *tup,
                              S((w * cap,), np.int64),
                              S((w * cap,), np.float64))


def _trace_patch_heavy(mesh):
    w, cap, S, vc, keys, valids, tup = _decl(mesh)
    fn = _unwrap(_patch_heavy_fn(mesh, 2, 1, (True,), (False,), 2))
    return jax.make_jaxpr(fn)(vc, S((2,), np.int32), *keys, *valids, *tup,
                              S((w * cap,), np.int64),
                              S((w * cap,), np.float64),
                              S((2,), np.int64), S((2,), np.float64))


def _trace_out_ltcount(mesh):
    w, cap, S, vc, keys, valids, tup = _decl(mesh)
    fn = _unwrap(_out_ltcount_fn(mesh, 2, 1, (True,), (False,)))
    return jax.make_jaxpr(fn)(vc, vc, *keys, *valids, *tup)


def _trace_stitch_pos(mesh):
    w, cap, S, vc, keys, valids, tup = _decl(mesh)
    i64 = np.int64
    fn = _unwrap(_stitch_pos_fn(mesh, 2, 1, (True,), (False,)))
    return jax.make_jaxpr(fn)(vc, vc, S((w,), i64), S((w,), i64),
                              S((w, 2), i64), S((w, 2), i64),
                              S((2,), i64), S((2,), i64),
                              S((), i64), *keys, *valids, *tup)


from ..analysis.registry import declare_builder, unwrap as _unwrap  # noqa: E402

declare_builder(f"{__name__}._heavy_count_fn", _trace_heavy_count,
                tags=("skew", "join"))
declare_builder(f"{__name__}._heavy_member_flag_fn", _trace_member_flag,
                tags=("skew", "join"))
declare_builder(f"{__name__}._heavy_partial_sum_fn",
                _trace_heavy_partial_sum, tags=("skew", "groupby"))
declare_builder(f"{__name__}._patch_heavy_fn", _trace_patch_heavy,
                tags=("skew", "groupby"))
declare_builder(f"{__name__}._out_ltcount_fn", _trace_out_ltcount,
                tags=("skew", "join"))
declare_builder(f"{__name__}._stitch_pos_fn", _trace_stitch_pos,
                tags=("skew", "join"))
