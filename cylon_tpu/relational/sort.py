"""Table-level sort: local multi-key sort + distributed sample sort.

TPU-native equivalent of the reference's sort stack — local
``Sort``/``SortIndicesMultiColumns`` (arrow_kernels.hpp:121) and
``DistributedSortRegularSampling`` (table.cpp:620: local sort -> uniform
sample -> splitter selection -> range partition -> ordered exchange -> local
merge).  Differences from the reference forced/afforded by the TPU model:

* splitter selection happens on the controller (single-controller SPMD), so
  the reference's Gather(samples->rank0) + Bcast(splitters) collectives
  (table.cpp:527,536) become a tiny host round-trip of W*m sampled rows;
* the per-rank split-point *binary search* (table.cpp:564-609) becomes a
  vectorized rows>splitters comparison (ops/pack.py rows_gt_splitters) —
  an O(n*W) VPU pass instead of O(n log n) comparator calls;
* the k-way merge of received sorted runs (table.cpp:436) is a plain local
  re-sort: ``lax.sort`` is a bitonic network on the VPU, where merging k runs
  has no advantage over sorting the whole shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .. import config
from ..utils.cache import jit, program_cache
from ..core.column import Column
from ..core.table import Table
from ..ctx.context import ROW_AXIS
from ..ops import pack
from ..ops import sort as sortk
from ..status import InvalidError
from ..utils.host import host_array
from .common import (PAD_L, REP, ROW, col_arrays, live_mask,
                     narrow32_flags, rebuild_like, sample_positions)
from .repart import exchange_by_targets
from ..parallel import shuffle

shard_map = jax.shard_map

#: samples per shard for splitter selection (reference SortOptions.num_samples;
#: 0 = scale with the world size, config.sort_samples)
DEFAULT_SAMPLES = 0

#: max payload lanes ridden through the local sort; wider tables switch to
#: one lane-matrix gather at the permutation
CARRY_LANE_BUDGET = 16


def _norm_dirs(by, ascending):
    if isinstance(ascending, bool):
        return tuple(not ascending for _ in by)
    if len(ascending) != len(by):
        raise InvalidError("ascending must match by length")
    return tuple(not a for a in ascending)


@program_cache()
def _local_sort_fn(mesh: Mesh, descendings: tuple, nulls_position: int,
                   narrow: tuple, vspec, f64_idx: tuple = (),
                   by_idx: tuple = (0,), donate: bool = False):
    """Per-shard multi-key sort.  Laneable columns RIDE THE SORT as u32
    payload lanes (~1.7 ns/row/lane measured) via ``vspec`` (a LaneSpec
    over the full column list, f64 columns planned laneless); f64 columns
    (positions ``f64_idx``) are gathered once at the stable permutation.

    Key columns are selected from ``datas``/``valids`` by the static
    ``by_idx`` positions rather than passed as separate operands: a key
    buffer must enter the program exactly ONCE for ``donate`` to be
    sound (donating one of two aliases of a buffer is a use-after-donate
    — lint rule TS108).  ``donate`` consumes the caller's column buffers
    (the pipeline's phase-1 sorts, whose inputs are exclusively owned
    fresh shuffle outputs): XLA reuses them for the sorted output
    instead of holding input + output live together."""
    from ..ops import lanes

    def per_shard(vc, datas, valids):
        by_datas = [datas[i] for i in by_idx]
        by_valids = [valids[i] for i in by_idx]
        cap = by_datas[0].shape[0]
        mask = live_mask(vc, cap)
        ko = pack.key_operands(list(by_datas), list(by_valids), row_mask=mask,
                               descendings=list(descendings),
                               nulls_position=nulls_position, pad_key=PAD_L,
                               narrow32=narrow or None)
        if vspec.n_lanes > CARRY_LANE_BUDGET or vspec.n_lanes == 0:
            # wide tables (or all-f64, nothing laneable): ONE lane-matrix
            # gather at the permutation (plus f64 side gathers inside
            # gather_columns) beats both per-column gathers and an
            # overloaded sort
            perm = sortk.sort_permutation(ko)
            return lanes.gather_columns(vspec, list(datas), list(valids),
                                        perm)
        vmat = lanes.pack_lanes(vspec, list(datas), list(valids))
        payloads = tuple(vmat[:, j] for j in range(vspec.n_lanes))
        need_perm = bool(f64_idx)
        if need_perm:
            payloads += (jnp.arange(cap, dtype=jnp.int32),)
        nk = len(ko.ops)
        sorted_all = jax.lax.sort(ko.ops + payloads, num_keys=nk,
                                  is_stable=True)
        smat = jnp.stack(sorted_all[nk:nk + vspec.n_lanes], axis=1)
        out_d, out_v = lanes.unpack_lanes(vspec, smat)
        out_d, out_v = list(out_d), list(out_v)
        if need_perm:
            perm = sorted_all[-1]
            for i in f64_idx:
                out_d[i] = datas[i][perm]
        return tuple(out_d), tuple(out_v)

    jit_kwargs = {"donate_argnums": (1, 2)} if donate else {}
    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, ROW, ROW),
                             out_specs=(ROW, ROW)), **jit_kwargs)


@program_cache()
def _sample_fn(mesh: Mesh, m: int, descendings: tuple, nulls_position: int,
               narrow: tuple = ()):
    """Uniform per-shard sample of transformed key operands (reference
    SampleTableUniform, util/arrow_utils.hpp:125)."""

    def per_shard(vc, by_datas, by_valids):
        cap = by_datas[0].shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        n = vc[my]
        ko = pack.key_operands(list(by_datas), list(by_valids),
                               descendings=list(descendings),
                               nulls_position=nulls_position,
                               narrow32=narrow or None)
        idx = sample_positions(n, m, cap)
        sampled = tuple(op[idx] for op in ko.ops)
        live = jnp.full((m,), True) & (n > 0)
        return sampled, live

    return jit(shard_map(per_shard, mesh=mesh, in_specs=(REP, ROW, ROW),
                             out_specs=(ROW, ROW)))


@program_cache()
def _target_fn(mesh: Mesh, descendings: tuple, nulls_position: int,
               narrow: tuple = ()):
    """Per-row destination rank = number of splitters strictly below the row
    (vectorized replacement of table.cpp:564-609 split-point binary search).
    ``narrow`` must match the sample fn's so splitter operands compare
    against structurally identical row operands."""

    def per_shard(vc, by_datas, by_valids, splitter_ops):
        cap = by_datas[0].shape[0]
        w = vc.shape[0]
        mask = live_mask(vc, cap)
        ko = pack.key_operands(list(by_datas), list(by_valids),
                               descendings=list(descendings),
                               nulls_position=nulls_position,
                               narrow32=narrow or None)
        gt = pack.rows_gt_splitters(ko, splitter_ops)
        # dtype pins the accumulator: plain sum(bool) widens the (cap, W-1)
        # operand to int64 under x64 (JX203) — W fits int32 trivially
        tgt = jnp.sum(gt, axis=1, dtype=jnp.int32)
        return jnp.where(mask, tgt, jnp.int32(w))

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, ROW, ROW, REP), out_specs=ROW))


def _pick_splitters(sample_ops, live, w: int):
    """Controller-side splitter selection: sort the W*m sampled operand rows
    (live first), take W-1 evenly spaced rows of the live prefix.  Any choice
    of actual sample rows yields a *correct* partition (rows are compared to
    splitters on device with the same total order); the choice only affects
    balance, so numpy's NaN-last lexsort is fine here."""
    ops_np = [host_array(o) for o in sample_ops]
    live_np = host_array(live)
    n_live = int(live_np.sum())
    # lexicographic argsort over (liveness, op_0, op_1, ...)
    cols = [~live_np] + [o for o in ops_np]
    order = np.lexsort(tuple(reversed(cols)))  # last key primary -> reverse
    take = []
    for j in range(1, w):
        pos = min(max((n_live * j) // w, 0), max(n_live - 1, 0))
        take.append(order[pos])
    take = np.asarray(take, np.int64)
    return tuple(o[take] for o in ops_np)


#: max u32 order lanes per string key (64 prefix bytes).  Past this the
#: single-process path falls back to exact dense ranks; multi-controller
#: raises (ranks are store-local, not value-stable).
MAX_ORDER_LANES = 16


def _expand_hashed_string_keys(table: Table, by: list, ascending):
    """Rewrite hashed-string sort keys into VALUE-STABLE big-endian byte
    lanes so the numeric sort machinery delivers lexical order.

    Per key: the store's unique values are Arrow-sorted on host, the max
    adjacent common prefix fixes the byte depth D that separates every
    distinct value, and each row's first-D bytes become ceil(D/4) int32
    lane columns (u32 big-endian, sign-flipped).  Lane tuples are equal
    iff the values are equal (D exceeds every distinct-pair common
    prefix), so the output's grouped_by contract still holds for the
    ORIGINAL key names.  Lanes are value-stable — every process computes
    identical lanes from its own store, so multi-controller range
    partitioning agrees without dictionary exchange (beyond one scalar
    max-depth agreement).

    Returns (table2, by2, ascending2, original_by) or None when no key is
    hashed.  Reference: the type-dispatched string sort kernels,
    arrow_kernels.hpp:53 IndexSortKernel<StringArray>."""
    from ..core.column import HashedStrings
    from ..core.dtypes import LogicalType
    from ..core.table import _put
    from .. import native
    env = table.env
    by_cols = [table.column(n) for n in by]
    if not any(isinstance(c.dictionary, HashedStrings) for c in by_cols):
        return None
    import jax
    import pyarrow as pa
    import pyarrow.compute as pc
    descend = _norm_dirs(by, ascending)
    if jax.process_count() > 1:
        # The lane DEPTH must cover the longest common prefix over every
        # DISTINCT value pair; with per-process value stores that bound is
        # not computable locally (process A's 'aaaa1' vs process B's
        # 'aaaa2' share 4 bytes that neither store sees as a pair).  A
        # wrong depth silently mis-sorts, so refuse rather than guess.
        raise InvalidError(
            "multi-controller sort on high-cardinality (hashed) string "
            "keys is not supported: per-process value stores cannot bound "
            "the cross-process common-prefix depth; dictionary-encode the "
            "column (low cardinality) or sort single-controller")
    w, cap = env.world_size, table.capacity
    vc = np.asarray(table.valid_counts, np.int64)
    live = np.zeros(w * cap, bool)
    for i in range(w):
        live[i * cap: i * cap + int(vc[i])] = True
    new_by, new_asc, add_cols = [], [], {}
    for n, c, desc in zip(by, by_cols, descend):
        if not isinstance(c.dictionary, HashedStrings):
            new_by.append(n)
            new_asc.append(not desc)
            continue
        hs, vs = c.dictionary._lookup()
        vs = np.asarray(vs, dtype=object)
        order = np.asarray(pc.sort_indices(
            pa.array(vs, type=pa.large_string())), np.int64)
        depth = native.max_adjacent_lcp(vs[order]) + 1
        n_lanes = -(-depth // 4)
        if n_lanes > MAX_ORDER_LANES:
            # exact dense-rank fallback (store-local, single process)
            ranks = np.empty(len(vs), np.uint32)
            ranks[order] = np.arange(len(vs), dtype=np.uint32)
            lanes = ranks[:, None]
            n_lanes = 1
        else:
            lanes = native.prefix_lanes(vs, n_lanes)        # (U, L) u32
            # +1 LENGTH lane: zero-padding is indistinguishable from a
            # real NUL byte, so values differing only by trailing NULs
            # ('ab' vs 'ab\0') encode identically at any depth — byte
            # length breaks exactly that tie (a strict prefix sorts
            # before its extensions, matching bytewise order)
            lens = native.utf8_lengths(vs).astype(np.uint32)
            lanes = np.concatenate([lanes, lens[:, None]], axis=1)
            n_lanes += 1
        codes = host_array(c.data)
        cu = codes.view(np.uint64) if codes.dtype == np.int64 \
            else codes.astype(np.uint64)
        if len(hs):
            idx = np.clip(np.searchsorted(hs, cu), 0, len(hs) - 1)
            ok = live if c.validity is None \
                else live & host_array(c.validity)
            if bool((hs[idx][ok] != cu[ok]).any()):
                raise InvalidError(
                    f"sort on string column {n!r}: some rows' codes are "
                    "missing from this process's value store (shuffled-in "
                    "rows from another controller); materialize first")
            row_lanes = lanes[idx]
        else:
            row_lanes = np.zeros((len(cu), n_lanes), np.uint32)
        # ONE device upload for all of this key's lanes (the tunnel
        # charges ~100 ms latency per buffer), sliced into columns
        # device-side
        mat = (row_lanes ^ np.uint32(0x80000000)).view(np.int32)
        placed = _put(np.ascontiguousarray(mat), env.sharding())
        for li in range(n_lanes):
            lane_host = mat[:, li]
            name = f"__strord_{n}_{li}"
            while name in table:
                name += "_"
            bounds = ((int(lane_host.min()), int(lane_host.max()))
                      if lane_host.size else None)
            add_cols[name] = Column(placed[:, li], LogicalType.INT32,
                                    c.validity, bounds=bounds)
            new_by.append(name)
            new_asc.append(not desc)
    return table.with_columns(add_cols), new_by, new_asc, list(by)


def sort_table(table: Table, by, ascending=True,
               nulls_position: str = "last",
               num_samples: int = DEFAULT_SAMPLES,
               method: str = "initial") -> Table:
    """Sort ``table`` globally by key columns ``by``.

    ``method`` selects the reference's two sample-sort strategies
    (table.cpp:761 dispatch):

    * ``"initial"`` (default) — ``DistributedSortInitialSampling``
      (table.cpp:692): sample the UNSORTED shards, range-partition, one
      local sort.  One sort pass; splitter quality rests on uniform
      position sampling.
    * ``"regular"`` — ``DistributedSortRegularSampling`` (table.cpp:620):
      LOCAL SORT first, then sample the sorted runs — evenly spaced
      positions of a sorted shard are its exact per-shard quantiles, so
      splitters are distribution-robust; costs a second local sort after
      the exchange (the reference pays a k-way merge there instead,
      :436 — on TPU a re-sort IS the merge, see module docstring)."""
    env = table.env
    by = [by] if isinstance(by, str) else list(by)
    if not by:
        raise InvalidError("sort needs at least one key column")
    from ..obs import plan as _plan
    with _plan.node("sort", by=tuple(by), method=method) as pn:
        if pn:
            pn.set(rows_in=table.row_count, rows_out=table.row_count)
        return _sort_table_impl(table, by, ascending, nulls_position,
                                num_samples, method, pn)


def _sort_table_impl(table: Table, by: list, ascending,
                     nulls_position: str, num_samples: int, method: str,
                     pn) -> Table:
    env = table.env
    from ..obs import plan as _plan
    # hashed-string keys: rewrite to value-stable byte lanes, sort on the
    # lanes, drop them — lexical order on arbitrary-cardinality strings
    expanded = _expand_hashed_string_keys(table, by, ascending)
    if expanded is not None:
        table2, by2, asc2, orig_by = expanded
        out = sort_table(table2, by2, asc2, nulls_position, num_samples,
                         method)
        synth = set(by2) - set(orig_by)
        cols = {n: c for n, c in out.columns.items() if n not in synth}
        res = Table(cols, env, out.valid_counts)
        # lane-tuple equality == value equality (the depth covers every
        # distinct pair's common prefix), so the grouped contract holds
        # for the original keys
        res.grouped_by = tuple(orig_by)
        return res
    descendings = _norm_dirs(by, ascending)
    npos = pack.NULL_FIRST if nulls_position == "first" else pack.NULL_LAST
    by_cols = [table.column(n) for n in by]
    from ..core.dtypes import LogicalType
    for n, c in zip(by, by_cols):
        if c.type == LogicalType.LIST:
            raise InvalidError(
                f"sort on list passthrough column {n!r} is not supported "
                "(codes are row ids, not value-ordered)")
    if method not in ("initial", "regular"):
        raise InvalidError("sort method must be 'initial' or 'regular'")
    w = env.world_size
    if method == "regular" and w > 1 and table.row_count > 0:
        # quantile-exact splitter samples come from the SORTED shards
        table = local_sort_table(table, by, ascending, nulls_position)
        by_cols = [table.column(n) for n in by]
    by_datas, by_valids = col_arrays(by_cols)
    vc = np.asarray(table.valid_counts, np.int32)

    narrow_keys = narrow32_flags(by_cols)
    if w > 1 and table.row_count > 0:
        # ---- range partition by sampled splitters ------------------------
        if num_samples <= 0:
            num_samples = config.sort_samples(w)
        m = min(max(table.capacity, 1), num_samples)
        if pn:
            # profiler piggyback on the splitter sampling path: the same
            # evenly-spaced per-shard positions (common.sample_positions)
            # feed a Misra-Gries key profile (obs/plan), so a skewed sort
            # key is named here before the range exchange concentrates
            # it.  It is a second small device program, not a reuse of
            # _sample_fn's outputs: those are TRANSFORMED sort operands
            # (direction-flipped, null-folded, bias-rebased — pack.
            # key_operands) from which the original key VALUES are not
            # recoverable.  Armed ANALYZE runs only.
            pn.annotate(route="sample_sort", num_samples=m,
                        splitters=w - 1)
            _plan.profile_keys(pn, table, by)
        sample_ops, live = _sample_fn(env.mesh, m, descendings, npos,
                                      narrow_keys)(
            vc, by_datas, by_valids)
        splitters = _pick_splitters(sample_ops, live, w)
        tgt = _target_fn(env.mesh, descendings, npos, narrow_keys)(
            vc, by_datas, by_valids, splitters)
        counts = shuffle.count_targets(env.mesh, tgt)
        table = exchange_by_targets(table, tgt, counts)

    # ---- local sort per shard -------------------------------------------
    out = local_sort_table(table, by, ascending, nulls_position)
    # globally sorted by the keys ⇒ equal keys contiguous per shard and
    # (range partition) co-located across shards
    out.grouped_by = tuple(by)
    return out


def local_sort_table(table: Table, by, ascending=True,
                     nulls_position: str = "last",
                     donate: bool = False) -> Table:
    """Per-shard local sort by ``by`` — no exchange: each shard's rows are
    reordered in place (the reference's local ``Sort``,
    arrow_kernels.hpp:121).  Used by :func:`sort_table` after its range
    exchange and by the range-partitioned pipeline (exec/pipeline.py) to
    sort the resident build side ONCE.  Unlike the public sort, hashed
    string keys are allowed here: callers that only need a *consistent*
    total order (range partitioning for equality joins) sort by the codes.

    Column bounds survive (the sort permutes the full padded row set, so
    each column's value multiset is unchanged).

    ``donate=True`` donates the table's column buffers into the sort
    program (docs/pipeline.md donation rules): the caller must own them
    EXCLUSIVELY — no other Table, Column or pending dispatch may alias
    them (the pipelined join donates only its fresh shuffle outputs, and
    only at ``world_size > 1``, where the shuffle guarantees freshness;
    a ``with_columns`` view of a user table shares buffers and must
    never be donated)."""
    env = table.env
    by = [by] if isinstance(by, str) else list(by)
    descendings = _norm_dirs(by, ascending)
    npos = pack.NULL_FIRST if nulls_position == "first" else pack.NULL_LAST
    by_cols = [table.column(n) for n in by]
    vc = np.asarray(table.valid_counts, np.int32)
    items = list(table.columns.items())
    names = [n for n, _ in items]
    # key columns ride inside datas/valids, selected by static position:
    # passing them as separate operands would alias each key buffer into
    # the program twice — unsound under donation (TS108)
    by_idx = tuple(names.index(n) for n in by)
    datas = tuple(c.data for _, c in items)
    valids = tuple(c.validity for _, c in items)
    from .common import table_lane_spec
    narrow = narrow32_flags(by_cols)
    vspec = table_lane_spec([c for _, c in items])
    f64_idx = tuple(i for i, c in enumerate(vspec.cols) if not c.lanes)
    out_d, out_v = _local_sort_fn(env.mesh, descendings, npos, narrow,
                                  vspec, f64_idx, by_idx, donate)(
        vc, datas, valids)
    cols = {}
    for (n, c), d, v in zip(items, out_d, out_v):
        cols[n] = Column(d, c.type, v, c.dictionary, bounds=c.bounds)
    # NOTE: deliberately does NOT set ``grouped_by`` — a per-shard sort
    # only guarantees per-shard contiguity, while grouped_by also asserts
    # cross-shard key co-location (it gates groupby's no-shuffle fast
    # path).  Call sites that additionally guarantee co-location (the
    # range exchange in sort_table, the hash shuffle in pipelined_join)
    # set it themselves.
    return Table(cols, env, table.valid_counts)


# ---------------------------------------------------------------------------
# trace-safety declarations (cylon_tpu.analysis.registry): the sample-sort
# builders are pure-local shard programs (splitter selection is a
# controller round-trip, the range exchange rides the shuffle engine) —
# the jaxpr pass asserts no hidden collective, no row-scale i32→i64
# widening, zero host callbacks.  docs/trace_safety.md.
# ---------------------------------------------------------------------------

def _decl_args(mesh, cap=1024):
    w = int(mesh.devices.size)
    S = jax.ShapeDtypeStruct
    vc = S((w,), np.int32)
    keys = (S((w * cap,), np.int64),)
    valids = (S((w * cap,), np.bool_),)
    return w, S, vc, keys, valids


def _trace_sample(mesh):
    _w, _S, vc, keys, valids = _decl_args(mesh)
    fn = _unwrap(_sample_fn(mesh, 64, (False,), pack.NULL_LAST, (False,)))
    return jax.make_jaxpr(fn)(vc, keys, valids)


def _trace_target(mesh):
    w, S, vc, keys, valids = _decl_args(mesh)
    sample = _unwrap(_sample_fn(mesh, 64, (False,), pack.NULL_LAST, (False,)))
    sampled, _live = jax.eval_shape(sample, vc, keys, valids)
    splitters = tuple(S((w - 1,), s.dtype) for s in sampled)
    fn = _unwrap(_target_fn(mesh, (False,), pack.NULL_LAST, (False,)))
    return jax.make_jaxpr(fn)(vc, keys, valids, splitters)


def _trace_local_sort(mesh):
    """The phase-1 local sort (ISSUE 6: donation changed its operand
    structure — keys selected from datas by static by_idx so each buffer
    enters the program exactly once, TS108): one nullable int32 lane
    column as the key + one f64 side column gathered at the stable
    permutation.  Pure-local, no collective, no widening."""
    from ..ops import lanes
    w = int(mesh.devices.size)
    cap, S = 1024, jax.ShapeDtypeStruct
    vspec = lanes.plan_lanes(("int32", "float64"), (True, False))
    fn = _unwrap(_local_sort_fn(mesh, (False,), pack.NULL_LAST, (False,),
                                vspec, (1,), (0,)))
    vc = S((w,), np.int32)
    datas = (S((w * cap,), np.int32), S((w * cap,), np.float64))
    valids = (S((w * cap,), np.bool_), None)
    return jax.make_jaxpr(fn)(vc, datas, valids)


from ..analysis.registry import declare_builder, unwrap as _unwrap  # noqa: E402

declare_builder(f"{__name__}._sample_fn", _trace_sample, tags=("sort",))
declare_builder(f"{__name__}._target_fn", _trace_target, tags=("sort",))
declare_builder(f"{__name__}._local_sort_fn", _trace_local_sort,
                tags=("sort",))
