"""Table-level join: local + distributed.

TPU-native equivalent of the reference's join stack — ``DistributedJoin``
(table.cpp:861: shuffle both tables by key hash, then local join) over the
local sort-join (join/sort_join.cpp:66, the reference's default algorithm,
join_config.hpp:37) with join_utils.cpp's output assembly (suffix naming,
null sides of outer joins).

The local kernel is the two-phase static-shape single-sort merge in
:mod:`cylon_tpu.ops.join` run per shard under ``shard_map``:

* phase 1 runs THE one stable sort of both sides' packed key tuples and
  returns exact per-shard output counts (the sidecar that replaces Arrow's
  growing builders) plus the per-position geometry carry as device arrays;
* the host picks a pow2 capacity;
* phase 2 reuses the carried geometry — no re-sort, no re-scan — to build
  (l_take, r_take) and gathers every output column through ONE u32
  lane-matrix gather per side (:mod:`cylon_tpu.ops.lanes`) instead of one
  gather per column — the dominant cost on TPU is per-gather, not per-lane.

Key packing consults host-known column bounds (``Column.bounds``) so int64
keys whose values fit in 32 bits sort as a single native operand.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .. import config
from ..obs import plan as _plan
from ..utils.cache import jit, program_cache
from ..core.column import Column
from ..core.table import Table
from ..ctx.context import ROW_AXIS
from ..ops import join as joink
from ..ops import lanes
from ..ops import pack
from ..status import InvalidError
from ..utils import timing
from ..utils.host import host_array
from .common import (PAD_L, PAD_R, REP, ROW, BoundedCache, build_table,
                     check_same_env,
                     sample_positions,
                     col_arrays, live_mask, narrow32_flags, promote_key_pair)
from .piece import PackedPiece
from .repart import shuffle_table

shard_map = jax.shard_map

HOW = ("inner", "left", "right", "outer", "semi", "anti")

#: capacity hysteresis: callsite-signature -> last exact output bucket.
#: Lets join_tables dispatch the materialize phase at the PREDICTED capacity
#: before the (blocking) count pull, overlapping the host sync with device
#: work; a mispredict (counts exceed the prediction) just re-dispatches at
#: the correct bucket.  Steady-state loops (benchmarks, iterative pipelines)
#: hit every time.
_CAP_CACHE = BoundedCache()

@program_cache()
def _hash_sample_fn(mesh: Mesh, m: int, nkeys: int):
    """Evenly spaced per-shard sample of the key tuple's ROW HASH —
    detection runs in hash space so multi-column and float keys work
    uniformly and the predicate is exactly the shuffle-routing hash
    (ops/hashing.hash_rows canonicalizes floats and folds validity)."""
    from ..ops import hashing

    def per_shard(vc, *args):
        datas = list(args[:nkeys])
        valids = list(args[nkeys:])
        cap = datas[0].shape[0]
        my = jax.lax.axis_index(ROW_AXIS)
        n = vc[my]
        h = hashing.hash_rows(datas, valids)
        idx = sample_positions(n, m, cap)
        live = jnp.full((m,), n > 0)
        return h[idx], live

    specs = (REP,) + (ROW,) * (2 * nkeys)
    return jit(shard_map(per_shard, mesh=mesh, in_specs=specs,
                             out_specs=(ROW, ROW)))


def _hash_args(cols):
    cap = cols[0].data.shape[0]
    datas = tuple(c.data for c in cols)
    valids = tuple(c.validity if c.validity is not None
                   else np.ones(cap, bool) for c in cols)
    return datas, valids


def _heavy_keys(table: Table, key_names: list, env):
    """Host-side heavy-hitter estimate from a small device sample: key
    HASHES whose weighted global share exceeds SKEW_GLOBAL_FACTOR/world
    (a single key owning a full shard's worth of rows).  Returns a small
    np uint32 array or None.  Reference analog: the sampled partition
    machinery (table.cpp:620-689) applied to skew (SURVEY.md §7 hard-part
    4).  A hash collision only widens the split to an extra (light) key —
    both sides flag with the same predicate, so joins stay exact."""
    w = env.world_size
    total = int(table.valid_counts.sum())
    if total < w * 64:  # too small to skew-split — skip the device sample
        return None
    cols = [table.column(n) for n in key_names]
    datas, valids = _hash_args(cols)
    m = config.SKEW_SAMPLE
    fn = _hash_sample_fn(env.mesh, m, len(cols))
    vc = np.asarray(table.valid_counts, np.int32)
    vals_d, live_d = fn(vc, *datas, *valids)
    vals = host_array(vals_d).reshape(w, m)
    live = host_array(live_d).reshape(w, m)
    # weight each shard's sample by its true row share — unweighted pooling
    # would let a tiny shard's keys dominate the global estimate
    shares: dict = {}
    for s in range(w):
        lv = vals[s][live[s]]
        if lv.size == 0:
            continue
        weight = float(table.valid_counts[s]) / total / lv.size
        uniq, cnt = np.unique(lv, return_counts=True)
        keep = cnt / lv.size > config.SKEW_MIN_SHARE
        for u, c in zip(uniq[keep], cnt[keep]):
            shares[u] = shares.get(u, 0.0) + c * weight
    thresh = config.SKEW_GLOBAL_FACTOR / w
    heavy = [(u, sh) for u, sh in shares.items() if sh > thresh]
    if not heavy:
        return None
    heavy.sort(key=lambda x: -x[1])
    return np.asarray([u for u, _ in heavy[:config.SKEW_MAX_KEYS]],
                      np.uint32)


@program_cache()
def _heavy_flag_fn(mesh: Mesh, k: int, nkeys: int):
    from ..ops import hashing

    def per_shard(heavy_hashes, *args):
        datas = list(args[:nkeys])
        valids = list(args[nkeys:])
        h = hashing.hash_rows(datas, valids)
        flag = jnp.zeros(h.shape[0], bool)
        for j in range(k):
            flag = flag | (h == heavy_hashes[j])
        return flag

    specs = (REP,) + (ROW,) * (2 * nkeys)
    return jit(shard_map(per_shard, mesh=mesh, in_specs=specs,
                             out_specs=ROW))


def _shuffle_for_join(lwork: Table, rwork: Table, left_on, right_on,
                      how: str, env):
    """Distributed co-location with adaptive heavy-key skew splitting.

    Default: hash-shuffle both sides (reference table.cpp:219).  For
    inner/left/right/outer joins with ``CYLON_TPU_SKEW_SPLIT`` armed
    (default), the probe side's sampled key distribution feeds the
    weighted Misra-Gries detector and any finalized :class:`~.skew.
    SkewPlan` (relational/skew.py — the plan facade, lint rule TS115)
    routes the exchange: each heavy key's probe rows land as
    fixed-stride global-order subsequences on the key's rank group
    (order-preserving salted sub-partitioning) and its build rows
    duplicate-broadcast to that group, so no shard ever receives a whole
    heavy key while the caller can stitch the output bit- and
    order-equal to the unsplit hash plan (docs/skew.md).  The plan is
    VOTED over the consensus wire before any split collective runs
    (``Code.SkewPlan``).

    semi/anti keep the legacy round-robin spread: their output is a
    filter of probe rows (no output expansion to rebalance, no stitch),
    and a fully replicated heavy build row lets ANY shard detect the
    match.

    Returns ``(lwork, rwork, split)`` — ``split`` is False (plain hash),
    True (broadcast / legacy spread: co-location broken, no plan), or
    the finalized :class:`~.skew.SkewPlan` (caller must stitch)."""
    from ..parallel import shuffle as shf
    from ..parallel.collectives import allgather_table
    from . import skew as skewmod
    from .repart import concat_tables, exchange_by_targets, filter_table

    # ---- broadcast join: replicate a SMALL side, shuffle NOTHING --------
    # (the classic broadcast-hash-join; reference analog: Bcast(Table) +
    # local join, net/communicator.hpp:51).  Safe only when the small
    # side's unmatched rows are never emitted (they would emit once per
    # replica): small-RIGHT for inner/left/semi/anti, small-LEFT for
    # inner/right.  The big side stays in place, so equal keys are NOT
    # co-located afterwards — the returned flag suppresses grouped_by and
    # the deferred fused pushdown exactly like the skew split does.
    bc = config.BROADCAST_JOIN_ROWS
    if (how in ("inner", "left", "semi", "anti")
            and rwork.row_count <= bc
            and lwork.row_count >= 4 * max(rwork.row_count, 1)):
        # countable path marker (tests/test_fuzz.py regime tier)
        timing.bump("join.broadcast")
        _plan.annotate(route="broadcast", broadcast_side="right")
        return lwork, allgather_table(rwork), True
    if (how in ("inner", "right")
            and lwork.row_count <= bc
            and rwork.row_count >= 4 * max(lwork.row_count, 1)):
        timing.bump("join.broadcast")
        _plan.annotate(route="broadcast", broadcast_side="left")
        return allgather_table(lwork), rwork, True

    if how in ("inner", "left", "right", "outer") and config.SKEW_SPLIT:
        # adaptive skew-split plan (relational/skew.py): detect heavy
        # probe keys, vote the plan, split + duplicate-broadcast.  The
        # escape hatch CYLON_TPU_SKEW_SPLIT=0 is the UNSPLIT baseline
        # the route's bit/order-equality contract is stated against.
        if how == "right":
            probe, probe_on = rwork, right_on
            build, build_on = lwork, left_on
        else:
            probe, probe_on = lwork, left_on
            build, build_on = rwork, right_on
        plan = skewmod.detect(probe, probe_on, env)
        if plan is not None:
            plan = skewmod.finalize_or_none(plan, probe, probe_on,
                                            build, build_on)
        if plan is not None:
            # vote rides the consensus wire BEFORE the split's first
            # collective; every rank adopts the identical plan hash
            skewmod.adopt(plan, env)
            _plan.annotate(route="skew_split", skew_plan=plan.summary())
            probe_out, build_out = skewmod.split_exchange(
                probe, probe_on, build, build_on, plan)
            if how == "right":
                return build_out, probe_out, plan
            return probe_out, build_out, plan
        _plan.annotate(skew_split_armed=True, skew_split_keys=0)

    if how in ("semi", "anti"):
        # legacy spread: output ⊆ left rows, and a replicated heavy
        # build row lets ANY shard detect the match
        probe, probe_on = lwork, left_on
        build, build_on = rwork, right_on
        heavy = _heavy_keys(probe, probe_on, env)
        if heavy is not None:
            bcols = [build.column(n) for n in build_on]
            bdatas, bvalids = _hash_args(bcols)
            flag = _heavy_flag_fn(env.mesh, len(heavy), len(bcols))(
                heavy, *bdatas, *bvalids)
            build_heavy = filter_table(build, flag)
            # replication guard: if the BUILD side is itself heavy on
            # these keys, W-way replication would recreate the blow-up
            # the split exists to avoid — fall back to plain hashing
            if (build_heavy.row_count * env.world_size
                    > config.SKEW_GUARD_RATIO * max(build.row_count, 1)
                    and build_heavy.row_count > config.SKEW_GUARD_ROWS):
                _plan.annotate(route="hash", skew_guard_fallback=True)
                return (shuffle_table(lwork, left_on),
                        shuffle_table(rwork, right_on), False)
            _plan.annotate(route="skew_split", heavy_keys=int(len(heavy)))
            build_light = filter_table(build, ~flag)
            build_out = concat_tables(
                [shuffle_table(build_light, build_on),
                 allgather_table(build_heavy)])
            pcols = [probe.column(n) for n in probe_on]
            pdatas, pvalids = _hash_args(pcols)
            tgt = shf.skew_targets(env.mesh, pdatas, pvalids,
                                   probe.valid_counts, heavy)
            counts = shf.count_targets(env.mesh, tgt)
            probe_out = exchange_by_targets(probe, tgt, counts)
            return probe_out, build_out, True
    return (shuffle_table(lwork, left_on), shuffle_table(rwork, right_on),
            False)


def _live_cat(vcl, vcr, cap_l: int, cap_r: int):
    """Concat-row liveness for (left ++ right) per shard."""
    return jnp.concatenate([live_mask(vcl, cap_l), live_mask(vcr, cap_r)])


def _sorted_state(vcl, vcr, l_datas, l_valids, r_datas, r_valids,
                  narrow: tuple, payloads: tuple = (),
                  all_live: bool = False):
    """Per-shard single-sort join state (bnd, idx_s, live_cat, sorted
    payloads).

    Both sides must build structurally identical operand lists, so the
    null-flag presence per key column is the union of the two sides' and the
    narrow-key decision is made by the caller for the pair.

    ``all_live=True`` (host-known: both tables' valid_counts == capacity)
    drops the row-liveness sort operand AND the downstream liveness gather
    (live_cat=None) — one less sort pass and one less ~15 ns/row gather."""
    cap_l, cap_r = l_datas[0].shape[0], r_datas[0].shape[0]
    mask_l = None if all_live else live_mask(vcl, cap_l)
    mask_r = None if all_live else live_mask(vcr, cap_r)
    need_nf = tuple((lv is not None) or (rv is not None)
                    for lv, rv in zip(l_valids, r_valids))
    ko_l = pack.key_operands(list(l_datas), list(l_valids), row_mask=mask_l,
                             pad_key=PAD_L, need_null_flags=need_nf,
                             narrow32=narrow)
    ko_r = pack.key_operands(list(r_datas), list(r_valids), row_mask=mask_r,
                             pad_key=PAD_R, need_null_flags=need_nf,
                             narrow32=narrow)
    bnd, idx_s, pl_s = joink.join_sort_state(ko_l, ko_r, payloads)
    live_cat = None if all_live \
        else jnp.concatenate([mask_l, mask_r])
    return bnd, idx_s, live_cat, pl_s


@program_cache()
def _semi_flag_fn(mesh: Mesh, narrow: tuple, all_live: bool, anti: bool):
    """Per-left-row matched flag for SEMI/ANTI joins over the single-sort
    state: one run of the boundary algebra (right-count per key run), no
    output expansion at all — the output is a filter of the left table.
    Null keys match null keys (pandas merge semantics, same as the other
    join types here).  Reference: the LEFT_SEMI/LEFT_ANTI shapes the C++
    core reaches via unmatched-count bookkeeping in its sort join
    (sort_join.cpp:66 ``advance()`` run extraction)."""

    def per_shard(vcl, vcr, l_datas, l_valids, r_datas, r_valids):
        cap_l = l_datas[0].shape[0]
        bnd, idx_s, live_cat, _pl = _sorted_state(
            vcl, vcr, l_datas, l_valids, r_datas, r_valids, narrow, (),
            all_live)
        n = bnd.shape[0]
        pos = jnp.arange(n, dtype=jnp.int32)
        side_r = idx_s >= cap_l
        if live_cat is None:
            lefts_b = ~side_r
            rights = side_r.astype(jnp.int32)
        else:
            live = live_cat[idx_s]
            lefts_b = (~side_r) & live
            rights = (side_r & live).astype(jnp.int32)
        first = bnd.astype(bool) | (pos == 0)
        s_r = jnp.cumsum(rights).astype(jnp.int32)
        ebnd = jnp.concatenate([first[1:], jnp.ones(1, bool)])
        imax = jnp.int32(2**31 - 1)
        e_r = jax.lax.cummin(jnp.where(ebnd, s_r, imax), reverse=True)
        b_r = jax.lax.cummax(jnp.where(first, s_r - rights, jnp.int32(0)))
        matched = (e_r - b_r) > 0
        keep = (matched ^ anti) & lefts_b
        tgt = jnp.where(lefts_b, idx_s, jnp.int32(cap_l))
        return jnp.zeros(cap_l + 1, bool).at[tgt].set(
            keep, mode="drop")[:cap_l]

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, REP, ROW, ROW, ROW, ROW),
                             out_specs=ROW))


@program_cache()
def _count_fn(mesh: Mesh, how: str, narrow: tuple,
              lspec: lanes.LaneSpec | None = None,
              rspec: lanes.LaneSpec | None = None, all_live: bool = False,
              slim: bool = False):
    """Phase 1: sort once; return per-shard exact counts + carried state.

    With ``lspec``/``rspec`` (inner/left joins over fully-laneable output
    columns), that side's u32 lane matrix RIDES THE SORT as payload
    operands — ~1.7 ns/row/lane (measured) vs ~15 ns/row for the gathers
    the materialize phase would otherwise pay: ``rspec`` kills the
    dependent ``idx_s[mpos]`` + right lane-matrix gathers, ``lspec`` folds
    the left values into the meta-stack gather that phase 2 already does.
    Payload layout: left (emit) lanes first, then right (match) lanes."""

    def per_shard(vcl, vcr, l_datas, l_valids, r_datas, r_valids,
                  lg_cols, lg_valids, rg_cols, rg_valids):
        cap_l = l_datas[0].shape[0]
        cap_r = r_datas[0].shape[0]
        payloads = ()
        if lspec is not None:
            lmat = lanes.pack_lanes(lspec, lg_cols, lg_valids)
            zr = jnp.zeros(cap_r, jnp.uint32)
            payloads += tuple(jnp.concatenate([lmat[:, j], zr])
                              for j in range(lspec.n_lanes))
        if rspec is not None:
            rmat = lanes.pack_lanes(rspec, rg_cols, rg_valids)
            zl = jnp.zeros(cap_l, jnp.uint32)
            payloads += tuple(jnp.concatenate([zl, rmat[:, j]])
                              for j in range(rspec.n_lanes))
        bnd, idx_s, live, pl_s = _sorted_state(
            vcl, vcr, l_datas, l_valids, r_datas, r_valids, narrow, payloads,
            all_live)
        n, carry = joink.join_carry(bnd, idx_s, live, cap_l, how)
        if slim:
            # deferred-join state: only what the fused consumer needs
            # (relational/fused.py) — dropping the other carry arrays frees
            # ~5 N-length buffers of HBM while the state is held; a later
            # materialization rebuilds the carry from (idx_s, bnd) with
            # scans alone (_carry_fn — the sort never runs twice)
            return (n.reshape(1), idx_s, bnd) + pl_s
        return (n.reshape(1),) + tuple(carry) + pl_s

    n_pl = (lspec.n_lanes if lspec is not None else 0) + \
        (rspec.n_lanes if rspec is not None else 0)
    n_out = (3 + n_pl) if slim else (7 + n_pl)
    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, REP, ROW, ROW, ROW, ROW, ROW,
                                       ROW, ROW, ROW),
                             out_specs=(ROW,) * n_out))


@program_cache()
def _carry_fn(mesh: Mesh, how: str, cap_l: int, cap_r: int,
              all_live: bool):
    """Recompute the full phase-1 carry from a held SLIM state (idx_s, bnd)
    — prefix scans only (~1 ns/row), no re-sort.  Used when a deferred
    join materializes: the slim outputs are a superset of what join_carry
    needs as inputs, so the dominant single-sort never runs twice."""

    def per_shard(vcl, vcr, idx_s, bnd):
        live = None if all_live else _live_cat(vcl, vcr, cap_l, cap_r)
        _, carry = joink.join_carry(bnd, idx_s, live, cap_l, how)
        return tuple(carry)

    return jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, REP, ROW, ROW),
                             out_specs=(ROW,) * 6))


@program_cache()
def _un_count_fn(mesh: Mesh):
    """Per-shard count of an OUTER join's appended unmatched-right rows
    (the carry's ``un`` flags) — the skew stitch needs the zone-B split
    of every shard's output to reconstruct the unsplit plan's row order
    (relational/skew.stitch_join_output).  One tiny pure-local sum."""

    def per_shard(un):
        return jnp.sum(un, dtype=jnp.int32).reshape(1)

    return jit(shard_map(per_shard, mesh=mesh, in_specs=ROW,
                             out_specs=ROW))


@program_cache()
def _materialize_fn(mesh: Mesh, how: str, out_cap: int, cap_l: int,
                    plan: tuple, lspec: lanes.LaneSpec,
                    rspec: lanes.LaneSpec, carry_emit: bool = False,
                    carry_match: bool = False):
    """Phase 2.  ``plan`` entries (static):
    ("l", i, needs_valid) — output column = left lane-matrix column i;
    ("r", j, needs_valid) — right lane-matrix column j;
    ("k", i, j, needs_valid) — coalesce left col i with right col j.

    ``carry_match``: the right lane matrix arrived pre-sorted as sort
    payload (phase 1) — right values come from ONE (out, Lr) gather of the
    sorted lanes at the match positions instead of idx_s[mpos] + a second
    lane-matrix gather.  ``carry_emit``: the left lane matrix arrived the
    same way and rides join_take's meta-stack gather — no separate left
    gather at all.  Both only for how in (inner, left)."""

    l_f64 = any(not c.lanes for c in lspec.cols)
    r_f64 = any(not c.lanes for c in rspec.cols)

    def per_shard(carry, pl_s, l_cols, l_valids, r_cols, r_valids):
        n_e = lspec.n_lanes if carry_emit else 0
        pl_e, pl_m = pl_s[:n_e], pl_s[n_e:]
        tk = joink.join_take(joink.JoinCarry(*carry), cap_l, how, out_cap,
                             extra=pl_e, carry_emit=carry_emit,
                             carry_match=carry_match,
                             emit_idx=carry_emit and l_f64,
                             match_idx=carry_match and r_f64)
        if carry_emit:
            emat = jnp.stack(tk.extra, axis=1)      # already at out slots
            ldat, lval = lanes.unpack_lanes(lspec, emat)
            l_ok = tk.valid
            if l_f64:   # carry-lite: f64 columns gather by take index
                ldat = list(ldat)
                for i, d in lanes.gather_laneless(lspec, l_cols,
                                                  tk.l_take).items():
                    ldat[i] = d
        else:
            ldat, lval = lanes.gather_columns(lspec, l_cols, l_valids,
                                              tk.l_take)
            l_ok = tk.l_take >= 0
        if carry_match:
            smat = jnp.stack(pl_m, axis=1)          # (N, Lr) sorted lanes
            rrows = smat[jnp.clip(tk.mpos, 0, smat.shape[0] - 1)]
            rdat, rval = lanes.unpack_lanes(rspec, rrows)
            r_ok = tk.matched
            if r_f64:
                rdat = list(rdat)
                for i, d in lanes.gather_laneless(rspec, r_cols,
                                                  tk.r_take).items():
                    rdat[i] = d
        else:
            rdat, rval = lanes.gather_columns(rspec, r_cols, r_valids,
                                              tk.r_take)
            r_ok = tk.r_take >= 0

        return _plan_outputs(plan, ldat, lval, l_ok, rdat, rval, r_ok)

    return jit(shard_map(
        per_shard, mesh=mesh,
        in_specs=(ROW, ROW, ROW, ROW, ROW, ROW),
        out_specs=(ROW, ROW)))


def _plan_outputs(plan, ldat, lval, l_ok, rdat, rval, r_ok):
    """Assemble the output (datas, valids) from per-side gathered columns
    per the static ``plan`` (traced; shared by the materialize programs)."""

    def side_out(datas, vals, ok, i, needs_valid):
        d = datas[i]
        if not needs_valid:
            return d, None
        v = ok if vals[i] is None else (ok & vals[i])
        return d, v

    out_d, out_v = [], []
    for entry in plan:
        if entry[0] == "k":
            _, i, j, needs_valid = entry
            dl, vl = side_out(ldat, lval, l_ok, i, True)
            dr, vr = side_out(rdat, rval, r_ok, j, True)
            d = jnp.where(l_ok, dl, dr)
            v = jnp.where(l_ok, vl, vr)
            out_d.append(d)
            out_v.append(v if needs_valid else None)
        else:
            side, i, needs_valid = entry
            datas, vals, ok = ((ldat, lval, l_ok) if side == "l"
                               else (rdat, rval, r_ok))
            d, v = side_out(datas, vals, ok, i, needs_valid)
            out_d.append(d)
            out_v.append(v)
    return tuple(out_d), tuple(out_v)


# ---------------------------------------------------------------------------
# packed-piece entry: joins that consume PackedPiece window descriptors
# (relational/piece.py) — the range-partitioned pipeline's fast path.  The
# window slice and lane unpack happen INSIDE the jitted join program,
# fused with key-operand construction: keys unpack first, payload lanes
# ride the phase-1 sort and unpack lazily in the carry/materialize stage.
# The seed's materialize-then-join path (PackedPiece.to_table + the normal
# colocated join) is the reference these programs are exactly equal to.
# ---------------------------------------------------------------------------

def _window(spec: lanes.LaneSpec, arrs, s, cap: int):
    """(lane-matrix window | None, tuple of f64 windows) of one side's
    packed arrays at per-shard offset ``s`` — dynamic slices only; XLA
    drops any window a consumer never reads."""
    has_mat = spec.n_lanes > 0
    mat = lanes.slice_lanes(spec, arrs[0], s, cap) if has_mat else None
    f64w = tuple(jax.lax.dynamic_slice(a, (s,), (cap,))
                 for a in arrs[int(has_mat):])
    return mat, f64w


def _window_keys(spec: lanes.LaneSpec, mat, f64w, key_idx: tuple):
    """Unpack ONLY the key columns from a window — the fused half of the
    seed's unpack-everything + re-pack-keys round trip."""
    fpos = {i: j for j, i in enumerate(
        i for i, c in enumerate(spec.cols) if not c.lanes)}
    datas, valids = [], []
    for i in key_idx:
        if spec.cols[i].lanes:
            d, v = lanes.unpack_column(spec, mat, i)
        else:
            d = f64w[fpos[i]]
            v = lanes.unpack_column(spec, mat, i)[1] if spec.n_lanes \
                else None
        datas.append(d)
        valids.append(v)
    return datas, valids


@program_cache()
def _packed_count_fn(mesh: Mesh, how: str, narrow: tuple, need_nf: tuple,
                     lspec: lanes.LaneSpec, rspec: lanes.LaneSpec,
                     kil: tuple, kir: tuple, cap_l: int, cap_r: int,
                     n_arrs_l: int, n_arrs_r: int, all_live: bool,
                     carry_emit: bool, carry_match: bool,
                     slim: bool = False):
    """Phase 1 over packed windows: slice both windows, unpack only the
    KEY columns, sort once, return per-shard exact counts + carried state.
    With ``carry_emit``/``carry_match`` the window's OWN lanes ride the
    sort as payload — there is no separate pack step at all (the windows
    already are lane matrices)."""

    def per_shard(vcl, vcr, sl, sr, *arrs):
        arrs_l, arrs_r = arrs[:n_arrs_l], arrs[n_arrs_l:]
        my = jax.lax.axis_index(ROW_AXIS)
        mat_l, f64_l = _window(lspec, arrs_l, sl[my], cap_l)
        mat_r, f64_r = _window(rspec, arrs_r, sr[my], cap_r)
        l_datas, l_valids = _window_keys(lspec, mat_l, f64_l, kil)
        r_datas, r_valids = _window_keys(rspec, mat_r, f64_r, kir)
        mask_l = None if all_live else live_mask(vcl, cap_l)
        mask_r = None if all_live else live_mask(vcr, cap_r)
        ko_l = pack.key_operands(l_datas, l_valids, row_mask=mask_l,
                                 pad_key=PAD_L, need_null_flags=need_nf,
                                 narrow32=narrow)
        ko_r = pack.key_operands(r_datas, r_valids, row_mask=mask_r,
                                 pad_key=PAD_R, need_null_flags=need_nf,
                                 narrow32=narrow)
        payloads = ()
        if carry_emit:
            zr = jnp.zeros(cap_r, jnp.uint32)
            payloads += tuple(jnp.concatenate([mat_l[:, j], zr])
                              for j in range(lspec.n_lanes))
        if carry_match:
            zl = jnp.zeros(cap_l, jnp.uint32)
            payloads += tuple(jnp.concatenate([zl, mat_r[:, j]])
                              for j in range(rspec.n_lanes))
        bnd, idx_s, pl_s = joink.join_sort_state(ko_l, ko_r, payloads)
        live_cat = None if all_live else jnp.concatenate([mask_l, mask_r])
        n, carry = joink.join_carry(bnd, idx_s, live_cat, cap_l, how)
        if slim:
            return (n.reshape(1), idx_s, bnd) + pl_s
        return (n.reshape(1),) + tuple(carry) + pl_s

    n_pl = (lspec.n_lanes if carry_emit else 0) + \
        (rspec.n_lanes if carry_match else 0)
    n_out = (3 + n_pl) if slim else (7 + n_pl)
    in_specs = (REP, REP, REP, REP) + (ROW,) * (n_arrs_l + n_arrs_r)
    return jit(shard_map(per_shard, mesh=mesh, in_specs=in_specs,
                             out_specs=(ROW,) * n_out))


@program_cache()
def _packed_materialize_fn(mesh: Mesh, how: str, out_cap: int, cap_l: int,
                           cap_r: int, plan: tuple,
                           lspec: lanes.LaneSpec, rspec: lanes.LaneSpec,
                           n_arrs_l: int, n_arrs_r: int,
                           carry_emit: bool, carry_match: bool,
                           donate: tuple = ()):
    """Phase 2 over packed windows.  Carried sides unpack from the sorted
    payload lanes exactly like :func:`_materialize_fn`; non-carried sides
    gather whole rows from the WINDOW lane matrix (one (out, L) gather —
    the matrix already exists, so there is no pack step) and unpack only
    at the output rows.  f64 side columns slice their window and gather by
    take index (carry-LITE, same as the monolith).

    ``donate``: argnums of per-piece phase-1 state this FINAL dispatch
    consumes — ``(0,)`` the carry tuple, ``(0, 1)`` carry + sorted
    payload lanes — so the steady-state loop reuses those buffers for
    the output instead of allocating fresh ones (docs/pipeline.md
    donation rules).  Never includes the window arrays (positions 4+):
    they are the packed SOURCE, shared by every remaining piece — a
    use-after-donate (lint rule TS108).  Callers donate only on the last
    dispatch over the state: the speculative-capacity dispatch and any
    fused consumer sharing the state via JoinState must not donate."""

    l_f64 = any(not c.lanes for c in lspec.cols)
    r_f64 = any(not c.lanes for c in rspec.cols)

    def f64_pick(spec, f64w, take):
        # spread the compact window list back to spec column slots so the
        # ONE laneless-gather implementation (lanes.gather_laneless)
        # serves both the packed and the monolithic materialize paths
        datas = [None] * len(spec.cols)
        wins = iter(f64w)
        for i, c in enumerate(spec.cols):
            if not c.lanes:
                datas[i] = next(wins)
        return lanes.gather_laneless(spec, datas, take)

    def per_shard(carry, pl_s, sl, sr, *arrs):
        arrs_l, arrs_r = arrs[:n_arrs_l], arrs[n_arrs_l:]
        my = jax.lax.axis_index(ROW_AXIS)
        n_e = lspec.n_lanes if carry_emit else 0
        pl_e, pl_m = pl_s[:n_e], pl_s[n_e:]
        tk = joink.join_take(joink.JoinCarry(*carry), cap_l, how, out_cap,
                             extra=pl_e, carry_emit=carry_emit,
                             carry_match=carry_match,
                             emit_idx=carry_emit and l_f64,
                             match_idx=carry_match and r_f64)
        mat_l, f64_l = _window(lspec, arrs_l, sl[my], cap_l)
        mat_r, f64_r = _window(rspec, arrs_r, sr[my], cap_r)
        if carry_emit:
            emat = jnp.stack(tk.extra, axis=1)      # already at out slots
            ldat, lval = lanes.unpack_lanes(lspec, emat)
            l_ok = tk.valid
            if l_f64:
                ldat = list(ldat)
                for i, d in f64_pick(lspec, f64_l, tk.l_take).items():
                    ldat[i] = d
        else:
            l_ok = tk.l_take >= 0
            if lspec.n_lanes:
                lrows = mat_l[jnp.clip(tk.l_take, 0, cap_l - 1)]
                ldat, lval = lanes.unpack_lanes(lspec, lrows)
                ldat, lval = list(ldat), list(lval)
            else:
                ldat = [None] * len(lspec.cols)
                lval = [None] * len(lspec.cols)
            for i, d in f64_pick(lspec, f64_l, tk.l_take).items():
                ldat[i] = d
        if carry_match:
            smat = jnp.stack(pl_m, axis=1)          # (N, Lr) sorted lanes
            rrows = smat[jnp.clip(tk.mpos, 0, smat.shape[0] - 1)]
            rdat, rval = lanes.unpack_lanes(rspec, rrows)
            r_ok = tk.matched
            if r_f64:
                rdat = list(rdat)
                for i, d in f64_pick(rspec, f64_r, tk.r_take).items():
                    rdat[i] = d
        else:
            r_ok = tk.r_take >= 0
            if rspec.n_lanes:
                rrows = mat_r[jnp.clip(tk.r_take, 0, cap_r - 1)]
                rdat, rval = lanes.unpack_lanes(rspec, rrows)
                rdat, rval = list(rdat), list(rval)
            else:
                rdat = [None] * len(rspec.cols)
                rval = [None] * len(rspec.cols)
            for i, d in f64_pick(rspec, f64_r, tk.r_take).items():
                rdat[i] = d
        return _plan_outputs(plan, ldat, lval, l_ok, rdat, rval, r_ok)

    in_specs = (ROW, ROW, REP, REP) + (ROW,) * (n_arrs_l + n_arrs_r)
    jit_kwargs = {"donate_argnums": tuple(donate)} if donate else {}
    return jit(shard_map(per_shard, mesh=mesh, in_specs=in_specs,
                             out_specs=(ROW, ROW)), **jit_kwargs)


def _fits32_meta(dtype, bounds) -> bool:
    """fits_int32 over piece metadata (physical dtype name + host bounds)."""
    dt = np.dtype(dtype)
    if dt.itemsize != 8 or dt.kind not in ("i", "u"):
        return False
    return bounds is not None and bounds[0] >= -(1 << 31) \
        and bounds[1] <= (1 << 31) - 1


def _same_dictionary(a, b) -> bool:
    if a is b:
        return True
    if a is None or b is None:
        return False
    if isinstance(a, np.ndarray) and isinstance(b, np.ndarray):
        return len(a) == len(b) and bool(np.array_equal(a, b))
    try:
        return bool(a == b)
    except Exception:  # noqa: BLE001 — exotic dictionary types: identity only
        return False


def _packed_keys_compatible(pl: PackedPiece, pr: PackedPiece,
                            left_on, right_on) -> bool:
    """Packed joins cannot promote keys inside the lanes — the pipeline
    promotes BEFORE packing, so pieces normally arrive aligned.  Any
    residual mismatch (dtype, dictionary code space) bails to the
    materialized path, which promotes like any other join."""
    for ln, rn in zip(left_on, right_on):
        i, j = pl.column_names.index(ln), pr.column_names.index(rn)
        if pl.spec.cols[i].dtype != pr.spec.cols[j].dtype:
            return False
        tl, tr = pl.meta[i][1], pr.meta[j][1]
        if tl != tr:
            return False
        dl, dr = pl.meta[i][2], pr.meta[j][2]
        if (dl is not None or dr is not None) \
                and not _same_dictionary(dl, dr):
            return False
    return True


class _LazyCounts:
    """A dispatched-but-not-pulled device count vector.  Sharing one
    instance between a DeferredTable's ``counts_thunk`` and its
    materialize thunk makes the host sync happen at most once, and only
    when someone actually needs the counts — a fused consumer that drains
    the join state never does (the piece loop's software pipeline)."""

    __slots__ = ("_dev", "value")

    def __init__(self, dev):
        self._dev = dev
        self.value = None

    def __call__(self) -> np.ndarray:
        if self.value is None:
            self.value = host_array(self._dev).astype(np.int64)
            self._dev = None
        return self.value


def _packed_statics(pl: PackedPiece, pr: PackedPiece, left_on, right_on,
                    how: str, suffixes, coalesce_keys: bool):
    """Derive every static input of the packed join programs (shared by
    the impl and the AOT prewarm)."""
    names_l, names_r = pl.column_names, pr.column_names
    kil = tuple(names_l.index(n) for n in left_on)
    kir = tuple(names_r.index(n) for n in right_on)
    need_nf = tuple((pl.spec.cols[i].valid_bit >= 0)
                    or (pr.spec.cols[j].valid_bit >= 0)
                    for i, j in zip(kil, kir))
    narrow = tuple(_fits32_meta(pl.spec.cols[i].dtype, pl.meta[i][3])
                   and _fits32_meta(pr.spec.cols[j].dtype, pr.meta[j][3])
                   for i, j in zip(kil, kir))

    coalesce = coalesce_keys and list(left_on) == list(right_on)
    key_set_l, key_set_r = set(left_on), set(right_on)
    overlap = (set(names_l) & set(names_r)) - (
        key_set_l if coalesce else set())
    plan, names, types, dicts, bounds = [], [], [], [], []
    for i, (n, t, dc, nb) in enumerate(pl.meta):
        has_v = pl.spec.cols[i].valid_bit >= 0
        if coalesce and n in key_set_l:
            j = kir[left_on.index(n)]
            _rn, _rt, _rdc, rnb = pr.meta[j]
            rv = pr.spec.cols[j].valid_bit >= 0
            bounds.append(None if nb is None or rnb is None
                          else (min(nb[0], rnb[0]), max(nb[1], rnb[1])))
            if how in ("inner", "left"):
                plan.append(("l", i, has_v))
            elif how == "right":
                plan.append(("r", j, rv))
            else:
                plan.append(("k", i, j, has_v or rv))
        else:
            plan.append(("l", i, has_v or how in ("right", "outer")))
            bounds.append(nb)
            n = n + suffixes[0] if n in overlap else n
        names.append(n)
        types.append(t)
        dicts.append(dc)
    for j, (n, t, dc, nb) in enumerate(pr.meta):
        if coalesce and n in key_set_r:
            continue
        rv = pr.spec.cols[j].valid_bit >= 0
        plan.append(("r", j, rv or how in ("left", "outer")))
        names.append(n + suffixes[1] if n in overlap else n)
        types.append(t)
        dicts.append(dc)
        bounds.append(nb)

    def can_carry(spec) -> bool:
        return bool(how in ("inner", "left")
                    and any(c.lanes for c in spec.cols))

    carry_emit = can_carry(pl.spec) and pl.spec.n_lanes <= 6
    carry_match = can_carry(pr.spec) and pr.spec.n_lanes <= 8
    all_live = bool((pl.lens == pl.piece_cap).all()
                    and (pr.lens == pr.piece_cap).all())
    return (kil, kir, need_nf, narrow, coalesce, tuple(plan), tuple(names),
            tuple(types), tuple(dicts), tuple(bounds), carry_emit,
            carry_match, all_live)


def prewarm_packed_join(pl: PackedPiece, pr: PackedPiece, left_on,
                        right_on, how: str, suffixes, allow_defer: bool,
                        coalesce_keys: bool = True) -> None:
    """AOT-compile the phase-1 program for this piece-pair SHAPE
    (``.lower().compile()`` — nothing executes): with per-range piece
    capacities precomputed, every distinct program can compile before the
    range loop starts instead of stalling dispatch mid-stream.  The
    executable lands in the persistent compile cache, where the in-process
    jit call path picks it up; best-effort — any failure just means the
    loop compiles lazily like the seed did."""
    if not (config.PREWARM_PIECE_PROGRAMS and config.COMPILE_CACHE_ENABLED):
        return
    try:
        (kil, kir, need_nf, narrow, coalesce, _plan, _names, _types,
         _dicts, _bounds, carry_emit, carry_match,
         all_live) = _packed_statics(pl, pr, left_on, right_on, how,
                                     suffixes, coalesce_keys)
        slim = (config.DEFER_JOIN and how == "inner" and carry_emit
                and carry_match and coalesce and allow_defer)
        fn = _packed_count_fn(
            pl.env.mesh, how, narrow, need_nf, pl.spec, pr.spec, kil, kir,
            pl.piece_cap, pr.piece_cap, len(pl.arrs), len(pr.arrs),
            all_live, carry_emit, carry_match, slim)
        vcl = np.asarray(pl.lens, np.int32)
        vcr = np.asarray(pr.lens, np.int32)
        from ..exec.compiler import aot_compile
        aot_compile(fn, vcl, vcr, pl.starts, pr.starts,
                    *pl.arrs, *pr.arrs)
    except Exception:  # noqa: BLE001 — best-effort warm only
        pass


def _join_packed_impl(pl: PackedPiece, pr: PackedPiece, left_on, right_on,
                      how: str, suffixes, coalesce_keys: bool,
                      allow_defer: bool) -> Table:
    env = pl.env
    if pr.env is not env and pr.env.mesh is not env.mesh:
        raise InvalidError("pieces belong to different CylonEnvs")
    # LRU bump for the HBM ledger: the spill tier's eviction order is
    # "cold first", measured by last piece-loop CONSUMPTION, not just
    # descriptor creation (exec/memory)
    from ..exec import memory
    memory.touch(pl.reg)
    memory.touch(pr.reg)
    (kil, kir, need_nf, narrow, coalesce, plan, names, types, dicts,
     bounds, carry_emit, carry_match, all_live) = _packed_statics(
        pl, pr, left_on, right_on, how, suffixes, coalesce_keys)
    cap_l, cap_r = pl.piece_cap, pr.piece_cap
    vcl = np.asarray(pl.lens, np.int32)
    vcr = np.asarray(pr.lens, np.int32)

    defer = (config.DEFER_JOIN and how == "inner" and carry_emit
             and carry_match and coalesce and allow_defer)
    fn = _packed_count_fn(env.mesh, how, narrow, need_nf, pl.spec, pr.spec,
                          kil, kir, cap_l, cap_r, len(pl.arrs),
                          len(pr.arrs), all_live, carry_emit, carry_match,
                          defer)
    args = (vcl, vcr, pl.starts, pr.starts) + pl.arrs + pr.arrs

    if defer:
        with timing.region("join.sort_count"):
            res = fn(*args)
        counts_dev, idx_s_s, bnd_s = res[0], res[1], res[2]
        pl_s = tuple(res[3:])
        # the counts stay ON DEVICE: the next piece's programs can be
        # enqueued before this piece's host sync, and a fused consumer
        # that drains the state never pulls them at all
        holder = _LazyCounts(counts_dev)

        def materialize_cols():
            counts = holder()
            out_cap = config.pow2ceil(int(counts.max())
                                      if counts.size else 1)
            with timing.region("join.materialize"):
                carry = _carry_fn(env.mesh, how, cap_l, cap_r, all_live)(
                    vcl, vcr, idx_s_s, bnd_s)
                # donate the freshly built carry (exclusively owned here)
                # but NOT pl_s — the JoinState shares those lanes with any
                # fused consumer that drains the deferred state (TS108)
                mfn = _packed_materialize_fn(
                    env.mesh, how, out_cap, cap_l, cap_r, plan, pl.spec,
                    pr.spec, len(pl.arrs), len(pr.arrs), True, True,
                    donate=(0,) if config.DONATE_BUFFERS else ())
                out_d, out_v = mfn(carry, pl_s, pl.starts, pr.starts,
                                   *pl.arrs, *pr.arrs)
            return {nme: Column(d, t, v, dc, bounds=b)
                    for nme, d, v, t, dc, b in
                    zip(names, out_d, out_v, types, dicts, bounds)}

        from ..core.table import DeferredTable
        from .fused import JoinState
        state = JoinState(
            vcl=vcl, vcr=vcr, idx_s=idx_s_s, bnd=bnd_s, pl_s=pl_s,
            lspec=pl.spec, rspec=pr.spec, plan=plan, names=names,
            types=types, dicts=dicts, key_names=tuple(left_on),
            cap_l=cap_l, cap_r=cap_r, all_live=all_live)
        out = DeferredTable(
            env, None, None, materialize_cols,
            (names, types, dicts, tuple(bool(e[-1]) for e in plan)),
            op_state=state, counts_thunk=holder)
        out.grouped_by = tuple(left_on)
        return out

    with timing.region("join.sort_count"):
        res = fn(*args)
        counts_dev, carry = res[0], res[1:7]
        pl_s = tuple(res[7:])
    cache_key = ("packed", env.serial, how, narrow, cap_l, cap_r,
                 int(pl.lens.sum()), int(pr.lens.sum()), tuple(left_on),
                 tuple(right_on), tuple(pl.column_names),
                 tuple(pr.column_names))
    predicted = _CAP_CACHE.get(cache_key)
    mat_args = (carry, pl_s, pl.starts, pr.starts) + pl.arrs + pr.arrs

    def mat_fn(cap, donate=()):
        return _packed_materialize_fn(
            env.mesh, how, cap, cap_l, cap_r, plan, pl.spec, pr.spec,
            len(pl.arrs), len(pr.arrs), carry_emit, carry_match,
            donate=donate)

    # phase-1 state (carry + sorted payload lanes) dies with this piece:
    # its LAST materialize dispatch donates it so the output reuses the
    # buffers.  The speculative dispatch below must NOT donate — a
    # capacity miss re-dispatches over the same state (TS108)
    final_donate = (0, 1) if config.DONATE_BUFFERS else ()
    with timing.region("join.materialize"):
        out_d = out_v = None
        if predicted is not None:
            # speculative dispatch at the predicted capacity BEFORE the
            # blocking count pull — the sync overlaps device work
            out_d, out_v = mat_fn(predicted)(*mat_args)
        counts = host_array(counts_dev).astype(np.int64)
        out_cap = config.pow2ceil(int(counts.max()) if counts.size else 1)
        _CAP_CACHE.put(cache_key, out_cap)
        if out_d is None or out_cap > predicted:
            out_d, out_v = mat_fn(out_cap, donate=final_donate)(*mat_args)
    out = build_table(names, out_d, out_v, types, dicts, counts, env,
                      bounds=bounds)
    if coalesce:
        # pieces are key-grouped (sorted windows) and hash-colocated —
        # same grouped contract as the colocated monolith
        out.grouped_by = tuple(left_on)
    return out


def _join_packed_entry(left, right, left_on, right_on, how, suffixes,
                       coalesce_keys, allow_defer):
    left_on = [left_on] if isinstance(left_on, str) else list(left_on)
    right_on = [right_on] if isinstance(right_on, str) else list(right_on)
    if len(left_on) != len(right_on) or not left_on:
        raise InvalidError("left_on/right_on must be equal-length, non-empty")
    pl = left if isinstance(left, PackedPiece) else None
    pr = right if isinstance(right, PackedPiece) else None
    use_packed = (config.PACKED_PIECES and pl is not None and pr is not None
                  and how in ("inner", "left", "right", "outer")
                  and _packed_keys_compatible(pl, pr, left_on, right_on))
    if use_packed:
        from ..exec.recovery import maybe_inject
        maybe_inject("join.piece_cap")  # CapacityOverflowError test point
        return _join_packed_impl(pl, pr, left_on, right_on, how, suffixes,
                                 coalesce_keys, bool(allow_defer))
    # no packed entry for this shape: materialize the window(s) and take
    # the normal colocated path (the equivalence reference)
    lt = pl.to_table() if pl is not None else left
    rt = pr.to_table() if pr is not None else right
    return join_tables(lt, rt, left_on, right_on, how=how,
                       suffixes=suffixes, coalesce_keys=coalesce_keys,
                       assume_colocated=True, allow_defer=allow_defer)


def join_tables(left: Table, right: Table, left_on, right_on,
                how: str = "inner", suffixes=("_x", "_y"),
                coalesce_keys: bool = True,
                assume_colocated: bool = False,
                allow_defer: bool | None = None) -> Table:
    """Join two tables. Distributed path = hash-shuffle both sides on the
    (promoted) keys, then per-shard local sort-join — the reference's exact
    skeleton (table.cpp:861,219,194).

    ``assume_colocated=True`` skips the shuffle: the caller guarantees equal
    keys already share a shard on both sides (pipelined execution shuffles
    the build side once and streams pre-shuffled probe chunks).

    Device OOM falls back to the range-partitioned pipeline
    (exec/pipeline.py — the reference's operator-DAG slot): the work tiles
    over key ranges so sort scratch and per-piece output each fit; retried
    at growing range counts.  Range disjointness makes the fallback valid
    for all four join types.

    ``left``/``right`` may be :class:`~cylon_tpu.relational.piece.
    PackedPiece` window descriptors instead of Tables (the pipelined range
    loop's fast path): the window slice + lane unpack then run INSIDE the
    jitted join program, fused with key-operand construction — no
    per-piece unpack→repack HBM round trip.  Packed inputs are colocated
    by construction and have no streaming fallback (the pieces ARE the
    streaming decomposition)."""
    from .common import run_with_oom_fallback

    if isinstance(left, PackedPiece) or isinstance(right, PackedPiece):
        # per-piece plan node (docs/pipeline.md): the window caps ARE the
        # piece geometry the pipelined node's children are judged by
        with _plan.node(
                "join.piece", how=how,
                cap_l=int(getattr(left, "piece_cap", 0)),
                cap_r=int(getattr(right, "piece_cap", 0))) as pn:
            if pn:
                pn.set(rows_in=int(getattr(left, "lens", np.zeros(1)).sum()
                                   + getattr(right, "lens",
                                             np.zeros(1)).sum()))
            res = _join_packed_entry(left, right, left_on, right_on, how,
                                     suffixes, coalesce_keys, allow_defer)
            if pn and type(res) is Table:
                pn.set(rows_out=res.row_count)
            return res

    def fallback(nc):
        from ..exec.pipeline import pipelined_join
        return pipelined_join(left, right, left_on, right_on, how=how,
                              n_chunks=nc, suffixes=suffixes)

    lo = [left_on] if isinstance(left_on, str) else list(left_on)
    ro = [right_on] if isinstance(right_on, str) else list(right_on)
    with _plan.node(
            "join", how=how, left_on=tuple(lo), right_on=tuple(ro),
            route=("colocated" if assume_colocated
                   or left.env.world_size == 1 else "hash")) as pn:
        if pn:
            pn.set(rows_in=left.row_count + right.row_count)
            _plan.profile_keys(pn, left, lo)
        res = run_with_oom_fallback(
            lambda: _join_tables_impl(left, right, left_on, right_on, how,
                                      suffixes, coalesce_keys,
                                      assume_colocated, allow_defer),
            can_fallback=(not assume_colocated and coalesce_keys
                          and how not in ("semi", "anti")),
            fallback=fallback, label="join", env=left.env)
        if pn and type(res) is Table:
            pn.set(rows_out=res.row_count)
        return res


def join_tables_multi(tables: list, ons: list, how: str = "inner",
                      suffixes=("_x", "_y")) -> Table:
    """N-way join on ONE shared key set: every table is co-partitioned
    ONCE (a single hash shuffle each — or a broadcast for small tables),
    then the chain runs as LOCAL colocated joins.  A naive binary chain
    re-shuffles the accumulated intermediate at every step; this issues
    exactly one exchange per input table.  Reference: the multi-table
    ``JoinTables`` overload, cpp/src/cylon/join/join.hpp:29.

    ``ons[i]``: key column name(s) of ``tables[i]`` (all key sets must be
    equal length; values are compared pairwise-promoted).  ``how`` applies
    to every step (inner/left)."""
    if len(tables) < 2 or len(tables) != len(ons):
        raise InvalidError("join_tables_multi needs >= 2 tables with one "
                           "key set each")
    if how not in ("inner", "left"):
        raise InvalidError("join_tables_multi supports how in "
                           "('inner','left') — chain others manually")
    ons = [[o] if isinstance(o, str) else list(o) for o in ons]
    if len({len(o) for o in ons}) != 1:
        raise InvalidError("all key sets must have the same length")
    env = tables[0].env
    # promote every table's keys to ONE representation BEFORE the
    # shuffles: the routing hash depends on the physical dtype (int64
    # hashes as two u32 lanes, int32 as one) and on string dictionaries
    # (table-local codes) — unpromoted shuffles would send equal keys to
    # different shards and the colocated chain would silently drop
    # matches.  Pairwise promotion converges on cols[0]; a second sweep
    # brings the middles to the final representation (same pattern as
    # concat_tables).
    tables = list(tables)
    for ki in range(len(ons[0])):
        cols = [t.column(ons[i][ki]) for i, t in enumerate(tables)]
        for j in range(1, len(cols)):
            cols[0], cols[j] = promote_key_pair(cols[0], cols[j])
        cols = [cols[0]] + [promote_key_pair(cols[0], c)[1]
                            for c in cols[1:]]
        tables = [t.with_columns({ons[i][ki]: c})
                  for i, (t, c) in enumerate(zip(tables, cols))]
    bc = config.BROADCAST_JOIN_ROWS
    big = max(t.row_count for t in tables)
    shuffled = []
    from ..parallel.collectives import allgather_table
    for i, (t, on) in enumerate(zip(tables, ons)):
        if env.world_size == 1:
            shuffled.append(t)
        elif (i > 0 and t.row_count <= bc
                and big >= 4 * max(t.row_count, 1)):
            # only RIGHT-side tables may replicate: a replicated LEFT
            # accumulator would emit its matches once per shard
            shuffled.append(allgather_table(t))
        else:
            shuffled.append(shuffle_table(t, on))
    acc = shuffled[0]
    acc_on = list(ons[0])
    for t, on in zip(shuffled[1:], ons[1:]):
        # Post-suffix tracking of the ACCUMULATED left key names (ADVICE
        # r5): when the key name sets are equal the keys coalesce onto the
        # left names; otherwise a left key colliding with a right column
        # is renamed with suffixes[0] (mirror of _join_tables_impl's
        # output plan).  The seed's fallback silently switched to the
        # RIGHT table's key names here — null for unmatched rows in a
        # `how='left'` chain, fabricating null-key matches downstream.
        coalesce = acc_on == on
        overlap = (set(acc.column_names) & set(t.column_names)) \
            - (set(acc_on) if coalesce else set())
        acc = join_tables(acc, t, acc_on, on, how=how, suffixes=suffixes,
                          assume_colocated=True, allow_defer=False)
        acc_on = [n if (coalesce or n not in overlap) else n + suffixes[0]
                  for n in acc_on]
        missing = [n for n in acc_on if n not in acc.column_names]
        if missing:
            raise InvalidError(
                f"accumulated join key column(s) {missing} disappeared "
                "after suffix renaming — choose non-colliding suffixes "
                "or rename the payload columns before join_tables_multi")
    acc.grouped_by = None
    return acc


def _join_tables_impl(left: Table, right: Table, left_on, right_on,
                      how: str = "inner", suffixes=("_x", "_y"),
                      coalesce_keys: bool = True,
                      assume_colocated: bool = False,
                      allow_defer: bool | None = None) -> Table:
    if how not in HOW:
        raise InvalidError(f"how must be one of {HOW}, got {how!r}")
    env = check_same_env(left, right)
    left_on = [left_on] if isinstance(left_on, str) else list(left_on)
    right_on = [right_on] if isinstance(right_on, str) else list(right_on)
    if len(left_on) != len(right_on) or not left_on:
        raise InvalidError("left_on/right_on must be equal-length, non-empty")

    # promote key pairs to comparable representations
    lkey_cols, rkey_cols = [], []
    for ln, rn in zip(left_on, right_on):
        a, b = promote_key_pair(left.column(ln), right.column(rn))
        lkey_cols.append(a)
        rkey_cols.append(b)
    lwork = left.with_columns(dict(zip(left_on, lkey_cols)))
    rwork = right.with_columns(dict(zip(right_on, rkey_cols)))

    from . import skew as skewmod

    skew_split = False
    skew_plan = None
    if env.world_size > 1 and not assume_colocated:
        with timing.region("join.shuffle"):
            lwork, rwork, skew_split = _shuffle_for_join(
                lwork, rwork, left_on, right_on, how, env)
        if isinstance(skew_split, skewmod.SkewPlan):
            # the caller-side half of the adaptive route: the local join
            # below runs unchanged over the split layout, then the
            # output stitches back into the UNSPLIT plan's global row
            # order (bit- and order-equal; docs/skew.md)
            skew_plan = skew_split

    l_key_cols = [lwork.column(n) for n in left_on]
    r_key_cols = [rwork.column(n) for n in right_on]
    l_datas, l_valids = col_arrays(l_key_cols)
    r_datas, r_valids = col_arrays(r_key_cols)
    narrow = narrow32_flags(l_key_cols, r_key_cols)
    vcl = np.asarray(lwork.valid_counts, np.int32)
    vcr = np.asarray(rwork.valid_counts, np.int32)

    if how in ("semi", "anti"):
        # output ⊆ left rows: one matched-flag pass + filter, no plan and
        # no expansion (reference: JoinTables' semi/anti shapes)
        all_live_sa = bool((vcl == lwork.capacity).all()
                           and (vcr == rwork.capacity).all())
        with timing.region("join.semi"):
            flag = _semi_flag_fn(env.mesh, narrow, all_live_sa,
                                 how == "anti")(
                vcl, vcr, l_datas, l_valids, r_datas, r_valids)
        from .repart import filter_table
        return filter_table(lwork, flag)

    cache_key = (env.serial, how, narrow, lwork.capacity, rwork.capacity,
                 int(lwork.valid_counts.sum()), int(rwork.valid_counts.sum()),
                 tuple(left_on), tuple(right_on),
                 tuple(lwork.column_names), tuple(rwork.column_names))
    predicted = _CAP_CACHE.get(cache_key)

    # ---- output plan -----------------------------------------------------
    coalesce = coalesce_keys and left_on == right_on
    key_set_l, key_set_r = set(left_on), set(right_on)
    overlap = (set(lwork.column_names) & set(rwork.column_names)) - (
        key_set_l if coalesce else set())

    # lane-matrix column lists per side (keys first, then gathered columns)
    l_cols_list: list[Column] = []
    r_cols_list: list[Column] = []

    def lane_col(side_list, col) -> int:
        side_list.append(col)
        return len(side_list) - 1

    plan, names, types, dicts, bounds = [], [], [], [], []

    def merged_bounds(a: Column, b: Column):
        if a.bounds is None or b.bounds is None:
            return None
        return (min(a.bounds[0], b.bounds[0]), max(a.bounds[1], b.bounds[1]))

    for n in lwork.column_names:
        col = lwork.column(n)
        if coalesce and n in key_set_l:
            ki = left_on.index(n)
            rcol = rwork.column(right_on[ki])
            bounds.append(merged_bounds(col, rcol))
            # the coalesced key only needs BOTH sides for outer joins; for
            # inner/left every output row has a live left key (and for right
            # a live right key) — one lane set instead of two
            if how in ("inner", "left"):
                plan.append(("l", lane_col(l_cols_list, col),
                             col.validity is not None))
            elif how == "right":
                plan.append(("r", lane_col(r_cols_list, rcol),
                             rcol.validity is not None))
            else:
                needs_valid = (col.validity is not None
                               or rcol.validity is not None)
                plan.append(("k", lane_col(l_cols_list, col),
                             lane_col(r_cols_list, rcol), needs_valid))
        else:
            needs_valid = col.validity is not None or how in ("right", "outer")
            plan.append(("l", lane_col(l_cols_list, col), needs_valid))
            bounds.append(col.bounds)
            n = n + suffixes[0] if n in overlap else n
        names.append(n)
        types.append(col.type)
        dicts.append(col.dictionary)
    for n in rwork.column_names:
        if coalesce and n in key_set_r:
            continue
        col = rwork.column(n)
        needs_valid = col.validity is not None or how in ("left", "outer")
        plan.append(("r", lane_col(r_cols_list, col), needs_valid))
        names.append(n + suffixes[1] if n in overlap else n)
        types.append(col.type)
        dicts.append(col.dictionary)
        bounds.append(col.bounds)

    # host-known bounds narrow 64-bit lanes to one u32 lane each
    from .common import table_lane_spec
    lspec = table_lane_spec(l_cols_list)
    rspec = table_lane_spec(r_cols_list)

    # ride a side's lane matrix through the phase-1 sort when every one of
    # its output columns is laneable (no f64 side channels) and the lane
    # count is small — payload operands cost ~1.7 ns/row vs ~15 ns/row
    # gathers.  carry_match (right side) kills the dependent idx_s[mpos] +
    # right lane-matrix gathers; carry_emit (left side) folds the left
    # values into the meta-stack gather join_take already performs.
    def _can_carry(spec, col_list, budget: int) -> bool:
        # laneless f64 columns do not disqualify (carry-LITE: laneable
        # columns ride the sort, f64 columns keep their take-index
        # gathers); there must be at least one laneable data column
        return bool(how in ("inner", "left") and col_list
                    and any(c.lanes for c in spec.cols)
                    and spec.n_lanes <= budget)

    carry_match = _can_carry(rspec, r_cols_list, 8)
    carry_emit = _can_carry(lspec, l_cols_list, 6)

    l_gather_args = (tuple(c.data for c in l_cols_list),
                     tuple(c.validity for c in l_cols_list))
    r_gather_args = (tuple(c.data for c in r_cols_list),
                     tuple(c.validity for c in r_cols_list))
    all_live = bool((vcl == lwork.capacity).all()
                    and (vcr == rwork.capacity).all())
    # phase 1 only consumes the columns that ride the sort; keep the
    # rest out of the trace (no needless retraces)
    count_l_args = l_gather_args if carry_emit else ((), ())
    count_r_args = r_gather_args if carry_match else ((), ())
    count_args = (vcl, vcr, l_datas, l_valids, r_datas, r_valids,
                  *count_l_args, *count_r_args)
    cl_spec = lspec if carry_emit else None
    cr_spec = rspec if carry_match else None

    # ---- deferred materialization (reference ops-DAG slot, C9) -----------
    # Inner joins whose output columns fully ride the phase-1 sort can hand
    # the pre-expansion sorted state to a fused downstream consumer
    # (groupby pushdown, relational/fused.py) — the output expansion (two
    # ~15 ns/slot gathers over every output row, the dominant join cost)
    # never runs for join->groupby-on-the-join-keys pipelines.  Any other
    # access materializes transparently (core.table.DeferredTable).  Phase 1
    # runs SLIM (no carry outputs, ~5 N-length HBM buffers freed) — a later
    # materialization rebuilds the carry from the held (idx_s, bnd) with
    # prefix scans only (_carry_fn) — the sort never runs twice.
    # allow_defer default: colocated (pipelined chunk) joins only defer
    # when the caller says a fused consumer will drain each chunk's state
    # immediately (pipelined_join with a sink).  The sink-less concat path
    # would retain every chunk's slim state simultaneously alongside the
    # resident build side — the HBM headroom the pipeline exists to keep.
    if allow_defer is None:
        allow_defer = not assume_colocated
    # the adaptive skew-split route (skew_plan) defers exactly like the
    # plain co-located join — the fused consumer combines the heavy
    # keys' per-shard partials (fused.py + skew.combine_heavy_partials),
    # any other access materializes THROUGH the stitch.  The plan-less
    # split=True legs (broadcast join / legacy semi-anti spread) have no
    # plan to reconstruct co-location from and stay eager.
    defer = (config.DEFER_JOIN and how == "inner" and carry_emit
             and carry_match and coalesce and allow_defer
             and (skew_plan is not None or not skew_split))
    if defer:
        with timing.region("join.sort_count"):
            res = _count_fn(env.mesh, how, narrow, cl_spec, cr_spec,
                            all_live, slim=True)(*count_args)
        counts_dev, idx_s_s, bnd_s = res[0], res[1], res[2]
        pl_s = tuple(res[3:])
        counts = host_array(counts_dev).astype(np.int64)
        out_cap = config.pow2ceil(int(counts.max()) if counts.size else 1)
        _CAP_CACHE.put(cache_key, out_cap)

        def materialize_cols():
            with timing.region("join.materialize"):
                # the slim state already holds the sorted payloads and
                # (idx_s, bnd); the carry rebuilds from scans alone — the
                # dominant single-sort does NOT run a second time
                carry = _carry_fn(env.mesh, how, lwork.capacity,
                                  rwork.capacity, all_live)(
                                      vcl, vcr, idx_s_s, bnd_s)
                fn = _materialize_fn(env.mesh, how, out_cap, lwork.capacity,
                                     tuple(plan), lspec, rspec, carry_emit,
                                     carry_match)
                out_d, out_v = fn(carry, pl_s, *l_gather_args,
                                  *r_gather_args)
            return {nme: Column(d, t, v, dc, bounds=b)
                    for nme, d, v, t, dc, b in
                    zip(names, out_d, out_v, types, dicts, bounds)}

        def fb(nc):
            from ..exec.pipeline import pipelined_join
            return pipelined_join(left, right, left_on, right_on,
                                  how=how, n_chunks=nc,
                                  suffixes=suffixes)

        def pre_table():
            # SPLIT-layout materialization (no stitch): the pre-stitch
            # table consume_unstitched hands an order-insensitive
            # consumer when the fused pushdown declined
            from .common import run_with_oom_fallback

            def mat():
                pre = Table(materialize_cols(), env, counts)
                pre.grouped_by = None
                return pre

            return run_with_oom_fallback(mat, True, fb,
                                         "deferred-join materialize",
                                         env=env)

        def thunk():
            # deferred materialization OOMs outside join_tables' wrapper —
            # give it the same streaming fallback; a fallback returns a
            # whole Table, which DeferredTable adopts (layout may differ)
            from .common import run_with_oom_fallback

            def mat():
                cols = materialize_cols()
                if skew_plan is None:
                    return cols
                # merge half of the adaptive route for a non-fused
                # consumer: stitch the split-layout output back into the
                # unsplit hash plan's global row order (docs/skew.md)
                pre = Table(cols, env, counts)
                pre.grouped_by = None
                with timing.region("join.skew_stitch"):
                    return skewmod.stitch_join_output(
                        pre, list(left_on), skew_plan, how, None)

            return run_with_oom_fallback(mat, True, fb,
                                         "deferred-join materialize",
                                         env=env)

        from ..core.table import DeferredTable
        from .fused import JoinState
        if skew_plan is not None:
            from .repart import even_partition_counts
            total = int(counts.sum())
            d_counts = even_partition_counts(total, env.world_size)
            d_cap = config.pow2ceil(int(d_counts.max()) if total else 1)
        else:
            d_counts, d_cap = counts, out_cap
        state = JoinState(
            vcl=vcl, vcr=vcr, idx_s=idx_s_s, bnd=bnd_s, pl_s=pl_s,
            lspec=lspec, rspec=rspec, plan=tuple(plan),
            names=tuple(names), types=tuple(types), dicts=tuple(dicts),
            key_names=tuple(left_on),
            cap_l=lwork.capacity, cap_r=rwork.capacity, all_live=all_live,
            skew_plan=skew_plan,
            pre_thunk=pre_table if skew_plan is not None else None)
        out = DeferredTable(
            env, d_counts, d_cap, thunk,
            (tuple(names), tuple(types), tuple(dicts),
             tuple(bool(e[-1]) for e in plan)),
            op_state=state)
        # a skew-split layout is not co-located (heavy keys span their
        # rank groups), and the stitched materialization is in global
        # row order on the even layout — neither satisfies grouped_by
        out.grouped_by = None if skew_plan is not None else tuple(left_on)
        return out

    with timing.region("join.sort_count"):
        res = _count_fn(env.mesh, how, narrow, cl_spec, cr_spec,
                        all_live)(*count_args)
        counts_dev, carry = res[0], res[1:7]
        pl_s = tuple(res[7:])

    mat_args = (carry, pl_s, *l_gather_args, *r_gather_args)

    with timing.region("join.materialize"):
        out_d = out_v = None
        if predicted is not None:
            # speculative dispatch at the predicted capacity BEFORE the
            # blocking count pull — the sync overlaps device work
            fn = _materialize_fn(env.mesh, how, predicted, lwork.capacity,
                                 tuple(plan), lspec, rspec, carry_emit,
                                 carry_match)
            out_d, out_v = fn(*mat_args)
        counts = host_array(counts_dev).astype(np.int64)
        out_cap = config.pow2ceil(int(counts.max()) if counts.size else 1)
        _CAP_CACHE.put(cache_key, out_cap)
        if out_d is None or out_cap > predicted:
            fn = _materialize_fn(env.mesh, how, out_cap, lwork.capacity,
                                 tuple(plan), lspec, rspec, carry_emit,
                                 carry_match)
            out_d, out_v = fn(*mat_args)
    out = build_table(names, out_d, out_v, types, dicts, counts, env,
                      bounds=bounds)
    if skew_plan is not None:
        # merge half of the adaptive route: per-row positions in the
        # UNSPLIT plan's global order + one order-preserving exchange
        # (repart.place_by_global_pos) — the result is bit- and
        # order-equal to the plain hash plan, on BALANCED shards.  The
        # stitch is DEFERRED (DeferredTable + skew.StitchState): an
        # order-insensitive consumer (groupby) takes the pre-stitch
        # table and the merge exchange never runs; any other access
        # stitches transparently.
        un_counts = None
        if how == "outer":
            # per-shard appended unmatched-right counts (zone B) from
            # the phase-1 carry's `un` flags — one tiny pull
            un_counts = host_array(_un_count_fn(env.mesh)(carry[5])) \
                .reshape(-1).astype(np.int64)
        if coalesce:
            key_out = list(left_on)
        elif how == "right":
            key_out = [n + suffixes[1] if n in overlap else n
                       for n in right_on]
        else:
            key_out = [n + suffixes[0] if n in overlap else n
                       for n in left_on]
        from .repart import even_partition_counts
        pre = out
        pre.grouped_by = None
        total = int(counts.sum())
        dest = even_partition_counts(total, env.world_size)

        def stitch_thunk():
            with timing.region("join.skew_stitch"):
                return skewmod.stitch_join_output(
                    pre, key_out, skew_plan, how, un_counts)

        from ..core.table import DeferredTable
        dt = DeferredTable(
            env, dest, config.pow2ceil(int(dest.max()) if total else 1),
            stitch_thunk,
            (tuple(names), tuple(types), tuple(dicts),
             tuple(bool(e[-1]) for e in plan)),
            op_state=skewmod.StitchState(pre, skew_plan, how, un_counts,
                                         key_out))
        dt.grouped_by = None
        return dt
    if coalesce and not skew_split:
        # join output rows are key-grouped per shard (sorted merge order) and
        # keys are co-located across shards (hash shuffle) -> groupby on the
        # same keys can skip shuffle + rank (relational/groupby.py fast path).
        # Skew splitting spreads heavy keys across shards, so the co-location
        # half of the contract does not hold there.
        out.grouped_by = tuple(left_on)
    return out


# ---------------------------------------------------------------------------
# trace-safety declarations (cylon_tpu.analysis.registry): the join kernels
# are pure-local shard programs — the jaxpr pass asserts NO collective ever
# appears in them (the shuffle happens upstream in parallel/shuffle.py), no
# row-scale i32→i64 widening, zero host callbacks.  docs/trace_safety.md.
# ---------------------------------------------------------------------------

def _decl_args(mesh, cap=1024):
    w = int(mesh.devices.size)
    S = jax.ShapeDtypeStruct
    vc = S((w,), np.int32)
    keys = (S((w * cap,), np.int64),)
    valids = (S((w * cap,), np.bool_),)
    return w, S, vc, keys, valids


def _trace_semi_flag(mesh):
    _w, _S, vc, keys, valids = _decl_args(mesh)
    fn = _unwrap(_semi_flag_fn(mesh, (False,), False, False))
    return jax.make_jaxpr(fn)(vc, vc, keys, valids, keys, valids)


def _trace_count(mesh):
    _w, _S, vc, keys, valids = _decl_args(mesh)
    fn = _unwrap(_count_fn(mesh, "inner", (False,), None, None, False, False))
    return jax.make_jaxpr(fn)(vc, vc, keys, valids, keys, valids,
                              (), (), (), ())


def _trace_carry(mesh):
    w, S, vc, _keys, _valids = _decl_args(mesh)
    cap = 1024
    fn = _unwrap(_carry_fn(mesh, "inner", cap, cap, False))
    cat = S((w * 2 * cap,), np.int32)
    return jax.make_jaxpr(fn)(vc, vc, cat, cat)


def _packed_decl_spec():
    # two non-null int32 lane columns: exercises window slice + key unpack
    # + payload carry without int64 lane reconstruction (which widens
    # i32→i64 by design and would trip JX203 in the trace)
    return lanes.plan_lanes(("int32", "int32"), (False, False))


def _trace_packed_count(mesh):
    w, S, vc, _keys, _valids = _decl_args(mesh)
    spec = _packed_decl_spec()
    cap = 512
    fn = _unwrap(_packed_count_fn(mesh, "inner", (False,), (False,), spec,
                                  spec, (0,), (0,), cap, cap, 1, 1, False,
                                  True, True, False))
    st = S((w,), np.int32)
    mat = S((w * 1024, spec.n_lanes), np.uint32)
    return jax.make_jaxpr(fn)(vc, vc, st, st, mat, mat)


def _trace_packed_materialize(mesh):
    w, S, vc, _keys, _valids = _decl_args(mesh)
    spec = _packed_decl_spec()
    cap = 512
    plan = (("l", 0, False), ("l", 1, False), ("r", 1, False))
    fn = _unwrap(_packed_materialize_fn(mesh, "inner", 1024, cap, cap,
                                        plan, spec, spec, 1, 1, False,
                                        False))
    carry = tuple(S((w * 2 * cap,), np.int32) for _ in range(6))
    st = S((w,), np.int32)
    mat = S((w * 1024, spec.n_lanes), np.uint32)
    return jax.make_jaxpr(fn)(carry, (), st, st, mat, mat)


from ..analysis.registry import declare_builder, unwrap as _unwrap  # noqa: E402

declare_builder(f"{__name__}._semi_flag_fn", _trace_semi_flag,
                tags=("join",))
# _count_fn's static key spans (how x narrow x lane-spec x liveness x
# slim) — a combinatorially larger legitimate program family than the
# capacity-keyed builders, so its session budget is wider
declare_builder(f"{__name__}._count_fn", _trace_count, tags=("join",),
                retrace_budget=128)
declare_builder(f"{__name__}._carry_fn", _trace_carry, tags=("join",))
# the packed-window programs span the same (how x narrow x lane-spec x
# liveness x slim) static family as _count_fn PLUS the per-range capacity
# pair — same widened session budget
declare_builder(f"{__name__}._packed_count_fn", _trace_packed_count,
                tags=("join", "pipeline"), retrace_budget=128)
declare_builder(f"{__name__}._packed_materialize_fn",
                _trace_packed_materialize, tags=("join", "pipeline"),
                retrace_budget=128)
