"""Table-level join: local + distributed.

TPU-native equivalent of the reference's join stack — ``DistributedJoin``
(table.cpp:861: shuffle both tables by key hash, then local join) over the
local sort-join (join/sort_join.cpp:66, the reference's default algorithm,
join_config.hpp:37) with join_utils.cpp's output assembly (suffix naming,
null sides of outer joins).

The local kernel is the two-phase static-shape sort-merge in
:mod:`cylon_tpu.ops.join` run per shard under ``shard_map``: phase 1 returns
exact per-shard output counts (the sidecar that replaces Arrow's growing
builders), the host picks a pow2 capacity, phase 2 materializes gather
indices and gathers every output column in one fused program.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .. import config
from ..core.column import Column
from ..core.table import Table
from ..ctx.context import ROW_AXIS
from ..ops import join as joink
from ..ops import pack
from ..ops import sort as sortk
from ..status import InvalidError
from .common import (PAD_L, PAD_R, REP, ROW, build_table, check_same_env,
                     col_arrays, live_mask, promote_key_pair)
from .repart import shuffle_table

shard_map = jax.shard_map

HOW = ("inner", "left", "right", "outer")


def _ranks(vcl, vcr, l_datas, l_valids, r_datas, r_valids):
    """Per-shard comparable dense ranks + liveness masks for both sides."""
    cap_l, cap_r = l_datas[0].shape[0], r_datas[0].shape[0]
    mask_l = live_mask(vcl, cap_l)
    mask_r = live_mask(vcr, cap_r)
    ko_l = pack.key_operands(list(l_datas), list(l_valids), row_mask=mask_l,
                             pad_key=PAD_L)
    ko_r = pack.key_operands(list(r_datas), list(r_valids), row_mask=mask_r,
                             pad_key=PAD_R)
    lids, rids, _ = pack.dense_rank_two(ko_l, ko_r)
    return lids, rids, mask_l, mask_r


@lru_cache(maxsize=None)
def _count_fn(mesh: Mesh, how: str):
    def per_shard(vcl, vcr, l_datas, l_valids, r_datas, r_valids):
        lids, rids, mask_l, mask_r = _ranks(vcl, vcr, l_datas, l_valids,
                                            r_datas, r_valids)
        n = joink.join_count(lids, rids, how, mask_l, mask_r)
        return n.reshape(1)

    return jax.jit(shard_map(per_shard, mesh=mesh,
                             in_specs=(REP, REP, ROW, ROW, ROW, ROW),
                             out_specs=ROW))


@lru_cache(maxsize=None)
def _materialize_fn(mesh: Mesh, how: str, out_cap: int, plan: tuple):
    """plan entries (static):
    ("l", needs_null_valid) / ("r", needs_null_valid) — gather arrays[i]
    from that side; ("k", needs_valid) — coalesce left/right key pair.
    Array operands arrive as parallel tuples (ldatas/lvalids/rdatas/rvalids
    for keys; gather columns in ``gcols``/``gvalids`` with side tags in the
    plan order)."""

    def per_shard(vcl, vcr, l_datas, l_valids, r_datas, r_valids,
                  gcols, gvalids):
        lids, rids, mask_l, mask_r = _ranks(vcl, vcr, l_datas, l_valids,
                                            r_datas, r_valids)
        l_take, r_take, _total = joink.join_indices(
            lids, rids, how, out_cap, mask_l, mask_r)
        out_d, out_v = [], []
        gi = 0
        for entry in plan:
            kind = entry[0]
            if kind == "k":
                _, ki, needs_valid = entry
                dl, vl = sortk.take_with_nulls(l_datas[ki], l_valids[ki], l_take)
                dr, vr = sortk.take_with_nulls(r_datas[ki], r_valids[ki], r_take)
                use_l = l_take >= 0
                d = jnp.where(use_l, dl, dr)
                v = jnp.where(use_l, vl, vr)
                out_d.append(d)
                out_v.append(v if needs_valid else None)
            else:
                take = l_take if kind == "l" else r_take
                needs_valid = entry[1]
                d, v = sortk.take_with_nulls(gcols[gi], gvalids[gi], take)
                out_d.append(d)
                out_v.append(v if needs_valid else None)
                gi += 1
        return tuple(out_d), tuple(out_v)

    return jax.jit(shard_map(
        per_shard, mesh=mesh,
        in_specs=(REP, REP, ROW, ROW, ROW, ROW, ROW, ROW),
        out_specs=(ROW, ROW)))


def join_tables(left: Table, right: Table, left_on, right_on,
                how: str = "inner", suffixes=("_x", "_y"),
                coalesce_keys: bool = True) -> Table:
    """Join two tables. Distributed path = hash-shuffle both sides on the
    (promoted) keys, then per-shard local sort-join — the reference's exact
    skeleton (table.cpp:861,219,194)."""
    if how not in HOW:
        raise InvalidError(f"how must be one of {HOW}, got {how!r}")
    env = check_same_env(left, right)
    left_on = [left_on] if isinstance(left_on, str) else list(left_on)
    right_on = [right_on] if isinstance(right_on, str) else list(right_on)
    if len(left_on) != len(right_on) or not left_on:
        raise InvalidError("left_on/right_on must be equal-length, non-empty")

    # promote key pairs to comparable representations
    lkey_cols, rkey_cols = [], []
    for ln, rn in zip(left_on, right_on):
        a, b = promote_key_pair(left.column(ln), right.column(rn))
        lkey_cols.append(a)
        rkey_cols.append(b)
    lwork = left.with_columns(dict(zip(left_on, lkey_cols)))
    rwork = right.with_columns(dict(zip(right_on, rkey_cols)))

    if env.world_size > 1:
        lwork = shuffle_table(lwork, left_on)
        rwork = shuffle_table(rwork, right_on)

    l_datas, l_valids = col_arrays([lwork.column(n) for n in left_on])
    r_datas, r_valids = col_arrays([rwork.column(n) for n in right_on])
    vcl = np.asarray(lwork.valid_counts, np.int32)
    vcr = np.asarray(rwork.valid_counts, np.int32)

    counts = np.asarray(_count_fn(env.mesh, how)(
        vcl, vcr, l_datas, l_valids, r_datas, r_valids)).astype(np.int64)
    out_cap = config.pow2ceil(int(counts.max()) if counts.size else 1)

    # ---- output plan -----------------------------------------------------
    coalesce = coalesce_keys and left_on == right_on
    l_nullable_side = how in ("right", "outer")   # left side may be unmatched
    r_nullable_side = how in ("left", "outer")
    key_set_l, key_set_r = set(left_on), set(right_on)
    overlap = (set(lwork.column_names) & set(rwork.column_names)) - (
        key_set_l if coalesce else set())

    plan, names, types, dicts, gcols, gvalids = [], [], [], [], [], []

    def add_gather(side, name, col, out_name):
        needs_valid = col.validity is not None or (
            l_nullable_side if side == "l" else r_nullable_side)
        plan.append((side, needs_valid))
        gcols.append(col.data)
        gvalids.append(col.validity)
        names.append(out_name)
        types.append(col.type)
        dicts.append(col.dictionary)

    for i, n in enumerate(lwork.column_names):
        if coalesce and n in key_set_l:
            ki = left_on.index(n)
            col = lwork.column(n)
            needs_valid = (col.validity is not None
                           or rwork.column(right_on[ki]).validity is not None)
            plan.append(("k", ki, needs_valid))
            names.append(n)
            types.append(col.type)
            dicts.append(col.dictionary)
        else:
            out = n + suffixes[0] if n in overlap else n
            add_gather("l", n, lwork.column(n), out)
    for n in rwork.column_names:
        if coalesce and n in key_set_r:
            continue
        out = n + suffixes[1] if n in overlap else n
        add_gather("r", n, rwork.column(n), out)

    fn = _materialize_fn(env.mesh, how, out_cap, tuple(plan))
    out_d, out_v = fn(vcl, vcr, l_datas, l_valids, r_datas, r_valids,
                      tuple(gcols), tuple(gvalids))
    return build_table(names, out_d, out_v, types, dicts, counts, env)
