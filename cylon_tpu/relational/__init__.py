"""Distributed relational operators (Table-level).

The TPU-native analog of the reference's Table API layer (reference
cpp/src/cylon/table.hpp:187-527 free functions + table.cpp): every
distributed operator follows the same skeleton the reference uses —
``partition locally -> exchange -> local kernel`` (docs/docs/arch.md:42-60) —
with the exchange being the padded ICI all-to-all in
:mod:`cylon_tpu.parallel.shuffle` and the local kernels the jit/SPMD vector
kernels in :mod:`cylon_tpu.ops`.

Local (serial) execution is the world-size-1 special case of the same code
path, mirroring the reference's ``world==1 -> local op`` dispatch
(table.cpp:866-868).
"""

from .join import join_tables, join_tables_multi  # noqa: F401
from .groupby import groupby_aggregate  # noqa: F401
from .sort import sort_table  # noqa: F401
from .setops import (equals, set_operation, unique_table)  # noqa: F401
from .repart import (concat_tables, filter_table, head, repartition,  # noqa: F401
                     repad_table, slice_table, shuffle_table, tail)
