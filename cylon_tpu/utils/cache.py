"""Compiled-program builder cache with mesh-scoped, globally bounded
entries.

Every shard_map/jit program factory in the framework is memoized on its
static arguments.  A plain ``functools.lru_cache`` keyed on the ``Mesh``
has two hazards the trace-safety analyzer (TS104) flags:

* **pinning** — the global cache holds the Mesh (and, through the jitted
  program's closure, every executable built for it) long after the
  owning ``CylonEnv`` is gone, and keeps doing so even on jax versions
  whose Mesh interning is weak;
* **cache-miss hazard** — two structurally identical meshes are distinct
  keys only by object identity quirks, so an innocently rebuilt mesh
  silently recompiles the whole program family.

:func:`program_cache` stores the per-mesh program table **on the mesh
object itself** (a descriptor-style key): structurally equal interned
meshes share one table, and this module adds no strong global reference
to any mesh.  Note the limit of that guarantee on current jax (0.4.x):
``Mesh.__new__`` interns instances in a strong module-level dict, so
meshes — and therefore their tables — live for the process regardless
of this cache.  To keep total retained executables bounded across
processes that cycle through many meshes, a module-level LRU of mesh
tables (:data:`MESH_TABLE_LIMIT`, weakly referenced) clears the
least-recently-used mesh's programs when the population overflows —
cleared entries rebuild on demand.

The wrapper also feeds the retrace sentinel
(:mod:`cylon_tpu.analysis.runtime`): each returned program is tagged
with its builder name, static key, and mesh identity so XLA compile
events can be attributed to the op that triggered them.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict

from .. import config

#: single per-mesh attribute holding {builder_qualname: OrderedDict}
_MESH_ATTR = "_cylon_tpu_program_cache"

#: max meshes with live program tables: jax interns meshes for the
#: process lifetime, so without this LRU a mesh-cycling process would
#: retain up to PROGRAM_CACHE_SIZE programs per builder PER MESH forever
MESH_TABLE_LIMIT = 8

#: id(mesh) -> (weakref-or-mesh, table); the LRU of live tables.  Holds
#: the mesh weakly (strongly only for exotic non-weakrefable mesh types,
#: where identity must be pinned to rule out id() reuse aliasing).
_TABLES: "OrderedDict[int, tuple]" = OrderedDict()

_lock = threading.RLock()


def _track_table(mesh, table) -> None:
    """Register a mesh's table in the global LRU; evict the oldest mesh's
    programs past MESH_TABLE_LIMIT (its table empties; entries rebuild on
    demand)."""
    def _on_collect(_r, k=id(mesh)):
        with _lock:  # RLock: safe even if GC fires inside a locked section
            _TABLES.pop(k, None)

    try:
        ref = weakref.ref(mesh, _on_collect)
    except TypeError:
        ref = mesh  # not weakrefable: pin (also rules out id() aliasing)
    _TABLES[id(mesh)] = (ref, table)
    while len(_TABLES) > MESH_TABLE_LIMIT:
        _oldest, (_ref, old_table) = _TABLES.popitem(last=False)
        n_programs = sum(len(lru) for lru in old_table.values())
        old_table.clear()
        # previously silent: the compile ledger counts the cleared
        # programs (compile_mesh_table_evict_total, docs/robustness.md)
        from ..exec import compiler
        compiler.on_table_evict(_oldest, n_programs)


def _mesh_table(mesh) -> dict:
    entry = _TABLES.get(id(mesh))
    if entry is not None:
        ref, table = entry
        referent = ref() if isinstance(ref, weakref.ref) else ref
        if referent is mesh:
            _TABLES.move_to_end(id(mesh))
            return table
        _TABLES.pop(id(mesh), None)  # id reuse after a mesh died
    table = getattr(mesh, _MESH_ATTR, None)
    if table is None:
        table = {}
        try:
            object.__setattr__(mesh, _MESH_ATTR, table)
        except (AttributeError, TypeError):
            pass  # tracked via _TABLES only
    _track_table(mesh, table)
    return table


class _LazyJit:
    """Deferred facade program: ``jax.jit`` + lifecycle wrap happen on
    the FIRST call (or attribute access), not at decoration time — so
    module-level ``@partial(jit, ...)`` kernels (ops/) never import the
    exec package mid-bootstrap."""

    # __weakref__: jax weakrefs callables it is handed (jit cache keys,
    # shard_map trace bookkeeping) — a slotted class without it fails
    # deep inside tracing with "cannot create weak reference"
    __slots__ = ("_fun", "_kw", "_prog", "__weakref__")

    def __init__(self, fun, kw):
        self._fun = fun
        self._kw = kw
        self._prog = None

    def _resolve(self):
        prog = self._prog
        if prog is None:
            from ..exec.compiler import jit as _jit
            prog = self._prog = _jit(self._fun, **self._kw)
        return prog

    def __call__(self, *args, **kwargs):
        return self._resolve()(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._resolve(), name)


def jit(fun=None, **kw):
    """The compile-lifecycle facade's ``jax.jit``, re-exported at the
    cache layer: operator modules bind ``jit`` from HERE at import time
    (``from ..utils.cache import jit``) because importing
    ``cylon_tpu.exec.compiler`` at module scope would pull the whole
    exec package — which imports the relational layer back (a cycle).
    The facade wrap is deferred to the first call (:class:`_LazyJit`);
    by then the exec package is importable.  Raw ``jax.jit`` outside
    this module and exec/compiler.py is a lint finding (TS117): every
    compile must ride the facade so the ledger, journal, watchdog and
    quarantine see it.  Usable directly (``jit(fn, **kw)``) or as a
    ``@partial(jit, static_argnames=...)`` decorator."""
    if fun is None:
        import functools
        return functools.partial(jit, **kw)
    return _LazyJit(fun, kw)


def program_cache(maxsize: int | None = None):
    """LRU-memoize a program factory whose FIRST argument is the Mesh.

    Per-mesh, per-builder bounded LRU (default
    ``config.PROGRAM_CACHE_SIZE``) living on the mesh object, with a
    global :data:`MESH_TABLE_LIMIT`-mesh bound — see module docstring.
    Remaining arguments must be hashable (the same contract
    ``lru_cache`` had).  Lookups are lock-protected; a concurrent miss
    may build the same program twice (harmless — last insert wins), the
    same semantics ``lru_cache`` has for in-flight calls.  The cached
    value is wrapped by the retrace sentinel's builder tag so compiles
    are attributable.
    """

    def deco(fn):
        name = f"{fn.__module__}.{fn.__qualname__}"
        limit = maxsize if maxsize is not None else config.PROGRAM_CACHE_SIZE

        def wrapper(mesh, *args, **kwargs):
            from ..analysis import runtime
            from ..exec import compiler
            key = (args, tuple(sorted(kwargs.items())) if kwargs else ())
            with _lock:
                table = _mesh_table(mesh)
                lru = table.get(name)
                if lru is None:
                    lru = table[name] = OrderedDict()
                hit = lru.get(key)
                if hit is not None:
                    lru.move_to_end(key)
            if hit is not None:
                runtime.note_builder(name, key, miss=False)
                compiler.on_hit(mesh, name, key)
                return hit
            runtime.note_builder(name, key, miss=True)
            built = fn(mesh, *args, **kwargs)
            # the retrace identity includes the mesh: the same static key
            # on another mesh (tests run 1/4/8-rank worlds side by side)
            # legitimately compiles once per mesh
            mesh_ident = (tuple(mesh.axis_names),
                          tuple(d.id for d in mesh.devices.flat))
            built = runtime.tag_program(name, built, (mesh_ident, key))
            popped = []
            with _lock:
                lru[key] = built
                while len(lru) > limit:
                    popped.append(lru.popitem(last=False)[0])
            # ledger hooks run OUTSIDE the cache lock (lock order:
            # cache._lock before compiler._lock; the budget vote may
            # ride the consensus wire and must never hold either lock)
            if popped:
                compiler.on_builder_evict(mesh, name, popped)
            compiler.on_insert(mesh, name, key, lru)
            return built

        def cache_clear(mesh=None):
            with _lock:
                if mesh is not None:
                    _mesh_table(mesh).pop(name, None)
                # without a mesh there is nothing global to clear —
                # tables live on the meshes themselves

        wrapper.cache_clear = cache_clear
        # lru_cache-compatible introspection: the per-mesh, per-builder LRU
        # bound (tests assert every factory in the package is bounded)
        wrapper.cache_parameters = lambda: {"maxsize": limit, "typed": False}
        wrapper.__wrapped__ = fn
        wrapper.__name__ = fn.__name__
        wrapper.__qualname__ = fn.__qualname__
        wrapper.__doc__ = fn.__doc__
        wrapper.__module__ = fn.__module__
        wrapper._is_program_cache = True
        return wrapper

    return deco
