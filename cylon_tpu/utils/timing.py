"""Per-phase bench timers (the reference's CYLON_BENCH_TIMER analog).

The reference wraps hot regions in a compile-time ``CYLON_BENCH_TIMER(ctx,
tag, ...)`` macro that prints ``[BENCH] tag ms`` on rank 0 when built with
``-D_CYLON_BENCH`` (util/macros.hpp:102-117).  Here the switch is the
runtime flag ``config.BENCH_TIMINGS`` (env ``CYLON_TPU_BENCH=1``): when off,
:func:`region` is a no-op context manager with near-zero overhead; when on,
wall-time per named region accumulates in a process-global table that
``bench.py`` snapshots into its phase-breakdown detail.

JAX dispatch is async — a region covering only device work would time the
dispatch, not the execution.  Regions are therefore placed around phases
that end in a host synchronization (count-matrix pulls, ``np.asarray`` of
sidecars); purely-async phases are flushed explicitly by the caller
(``block=`` argument) when exact attribution matters.

**The observer effect, and the async mode.**  Those explicit flushes
(:func:`maybe_block`) SERIALIZE piece production against piece compute —
exactly the overlap the pipelined operators exist for — so blocking
attribution both slows the profiled iteration and HIDES overlap wins in
the phase numbers.  ``CYLON_TPU_TIMING=async`` (config.TIMING_ASYNC)
keeps the regions as dispatch-only markers: ``maybe_block`` becomes a
no-op, each region records only the host time it took to ENQUEUE its
work, and the caller blocks once at iteration end (bench.py's final
output sync).  Phase numbers then read as "host time to dispatch": a
phase that stops dominating dispatch has genuinely left the critical
path.  Exact per-phase device attribution still needs ``block`` mode.

**Session-scoped attribution.**  The serving tier
(:mod:`cylon_tpu.exec.scheduler`) interleaves many tenants' queries on
one mesh, and a single process-global table would blend their phases —
tenant A's ``pipe.piece_join`` seconds indistinguishable from tenant
B's.  :func:`attribution_scope` opens a PRIVATE phase table routed by
thread identity: every :func:`region`/:func:`bump`/:func:`add_bytes` on
the scoped thread also lands in the scope's table (regions time
unconditionally inside a scope, independent of ``CYLON_TPU_BENCH`` —
the fair-share policy needs per-session dispatch seconds even in
production runs).  Scopes on different threads are DISJOINT by
construction — no cross-tenant attribution bleed — while the
process-global table keeps accumulating the union exactly as before
(``bench.py``'s snapshot is unchanged).  :func:`last_region` is
likewise scope-local when a scope is active, so a watchdog fault raised
on one tenant's thread carries that tenant's phase breadcrumb, not a
neighbor's.
"""

from __future__ import annotations

import contextlib
import threading
import time

from .. import config

#: name -> [total_seconds, call_count]
_ACCUM: dict[str, list] = {}

#: most recently entered region name — the exchange watchdog attaches it
#: to RankDesyncError as the last-known phase (always tracked, even with
#: timings off: one list-slot store per region)
_LAST_REGION = [""]

#: per-thread stack of active AttributionScopes (serving sessions run on
#: their own threads, so thread identity IS session identity here).  The
#: same TLS carries the thread's cumulative baton-park seconds
#: (``.excluded``): the PROCESS-GLOBAL table nets a region's own
#: thread's park time out exactly like the scope table does — a region
#: spanning a serving yield must not charge co-tenants' slices to the
#: global phase either (the fair-share no-bleed invariant, now applied
#: to both tables).
_SCOPE_TLS = threading.local()

#: the observability trace sink (cylon_tpu.obs.trace installs the armed
#: flight recorder here): every region exit becomes a timeline span,
#: every bump/add_bytes an instant.  One list load per region when
#: unarmed — the trace tier's whole happy-path cost in this module.
_TRACE: list = [None]


class AttributionScope:
    """One session's private phase table — see module docstring.  Obtain
    via :func:`attribution_scope`; read with :meth:`snapshot` (same shape
    as the module-level :func:`snapshot`) and :meth:`total_seconds` (the
    fair-share policy's accumulated-dispatch-time input)."""

    __slots__ = ("tag", "last", "_accum", "_bytes", "_excluded")

    def __init__(self, tag: str = ""):
        self.tag = tag
        self.last = ""
        self._accum: dict[str, list] = {}
        self._bytes: dict[str, int] = {}
        #: cumulative seconds this thread spent parked at the serving
        #: baton (scheduler._yield_turn) — subtracted from any region
        #: whose window contains the park, so a yield INSIDE a region
        #: (join.shuffle, pipe.consume) never charges co-tenants' slices
        #: to this scope's phase table or fair-share clock
        self._excluded = 0.0

    def _add(self, name: str, dt: float, n: int = 1) -> None:
        acc = self._accum.setdefault(name, [0.0, 0])
        acc[0] += dt
        acc[1] += n

    def _add_bytes(self, name: str, nbytes: int) -> None:
        self._bytes[name] = self._bytes.get(name, 0) + int(nbytes)
        self._accum.setdefault(name, [0.0, 0])

    def total_seconds(self) -> float:
        return sum(v[0] for v in self._accum.values())

    def absorb(self, other: "AttributionScope") -> None:
        """Merge another scope's table into this one — the plan
        profiler's node scopes shadow an enclosing serving-session scope
        exactly like nested scopes always did, so on node exit the
        node's SELF table is absorbed into the session scope: the
        tenant's fair-share clock and phase table see the same seconds
        with profiling on or off (obs/plan.py)."""
        for k, v in other._accum.items():
            self._add(k, v[0], v[1])
        for k, b in other._bytes.items():
            self._add_bytes(k, b)

    def snapshot(self) -> dict:
        out = {}
        for k, v in sorted(self._accum.items(), key=lambda kv: -kv[1][0]):
            ent = {"s": round(v[0], 4), "n": v[1]}
            if self._bytes.get(k):
                ent["b"] = self._bytes[k]
            out[k] = ent
        return out


def _scope() -> AttributionScope | None:
    stack = getattr(_SCOPE_TLS, "stack", None)
    return stack[-1] if stack else None


def exclude_from_scope(seconds: float) -> None:
    """Mark ``seconds`` of the current thread's wall time as NOT this
    thread's work — the serving scheduler calls this with the time a
    session spent parked at the baton, so regions spanning a yield point
    attribute only the tenant's own dispatch time (no co-tenant bleed
    into phase tables or the fair-share clock).  Nets out of BOTH the
    active scope's table and the process-global ``_ACCUM`` table (the
    global phase seconds previously absorbed co-tenants' slices inside
    spanning regions)."""
    s = float(seconds)
    _SCOPE_TLS.excluded = getattr(_SCOPE_TLS, "excluded", 0.0) + s
    sc = _scope()
    if sc is not None:
        sc._excluded += s


@contextlib.contextmanager
def attribution_scope(tag: str = ""):
    """Route this THREAD's regions/bumps/byte attributions into a private
    :class:`AttributionScope` (in addition to the process-global table)
    until exit.  Nested scopes shadow (innermost wins).  Yields the
    scope; its table survives the exit for later snapshots."""
    sc = AttributionScope(tag)
    stack = getattr(_SCOPE_TLS, "stack", None)
    if stack is None:
        stack = _SCOPE_TLS.stack = []
    stack.append(sc)
    try:
        yield sc
    finally:
        stack.pop()


@contextlib.contextmanager
def region(name: str, block=None):
    """Time a named region (when ``config.BENCH_TIMINGS`` — or always,
    scope-locally, inside an :func:`attribution_scope`).  ``block`` may be
    a jax array (or pytree leaf list) to block_until_ready before stopping
    the clock, charging async device work to this region."""
    sc = _scope()
    if sc is not None:
        sc.last = name
    else:
        _LAST_REGION[0] = name
    if not config.BENCH_TIMINGS and sc is None and _TRACE[0] is None:
        yield
        return
    t0 = time.perf_counter()
    ex0 = sc._excluded if sc is not None else 0.0
    gex0 = getattr(_SCOPE_TLS, "excluded", 0.0)
    try:
        yield
    finally:
        if block is not None and not config.TIMING_ASYNC:
            import jax
            jax.block_until_ready(block)
        dt = time.perf_counter() - t0
        tr = _TRACE[0]
        if tr is not None:
            tr.span(name, t0, dt)
        if config.BENCH_TIMINGS:
            # baton-park time that fell inside this region's window is
            # not this THREAD's work (exclude_from_scope); like the
            # scope table below, the global table nets it out — the
            # cumulative counters handle nesting correctly
            gnet = getattr(_SCOPE_TLS, "excluded", 0.0) - gex0
            acc = _ACCUM.setdefault(name, [0.0, 0])
            acc[0] += max(dt - gnet, 0.0)
            acc[1] += 1
        if sc is not None:
            sc._add(name, max(dt - (sc._excluded - ex0), 0.0))


#: snapshot-key suffix marking a BLOCKING host-sync region — the
#: dispatch/block attribution split (``split_snapshot``)
BLOCK_SUFFIX = ".block"


@contextlib.contextmanager
def sync_region(name: str):
    """Time a deliberate blocking host pull under ``name + '.block'``.

    The async attribution mode (``CYLON_TPU_TIMING=async``) turns every
    :func:`region` into a dispatch-only marker; the wall time those
    markers no longer capture is spent at the few designated sync points
    (the pipelined join's batched phase pull, per-piece count/meta
    pulls, the bench driver's final output sync).  Wrapping exactly those
    pulls in ``sync_region`` splits each phase into *dispatch* time (its
    plain region) and *block* time (its ``.block`` twin), so phase
    overlap is directly measurable: a phase that overlaps well shows
    near-zero dispatch AND near-zero block — its device work hides under
    another phase's block point."""
    with region(name if name.endswith(BLOCK_SUFFIX)
                else name + BLOCK_SUFFIX):
        yield


def split_snapshot(snap: dict) -> tuple[dict, dict]:
    """Split a :func:`snapshot` into ``(dispatch, block)`` second-maps:
    ``.block``-suffixed regions (``sync_region``) land in ``block`` under
    their base name; everything else is dispatch(-or-blocking-mode)
    attribution."""
    dispatch, block = {}, {}
    for k, v in snap.items():
        if k.endswith(BLOCK_SUFFIX):
            block[k[:-len(BLOCK_SUFFIX)]] = v["s"]
        else:
            dispatch[k] = v["s"]
    return dispatch, block


def maybe_block(x) -> None:
    """block_until_ready(x) ONLY when bench timings are on AND the timing
    mode is blocking — lets a region charge async device work to itself
    for attribution without serializing dispatch in production runs.  In
    async mode (``CYLON_TPU_TIMING=async``) this is a no-op even while
    timing: regions become dispatch-only markers and the caller blocks
    once at iteration end, so the measurement no longer perturbs the
    dispatch/compute overlap it measures."""
    if config.BENCH_TIMINGS and not config.TIMING_ASYNC:
        import jax
        jax.block_until_ready(x)


def last_region() -> str:
    """Name of the most recently entered region ("" before the first) —
    the failure-recovery watchdog's last-known-phase breadcrumb.  Inside
    an :func:`attribution_scope` this is the SCOPE's last region, so a
    fault on one serving session's thread never reports a co-tenant's
    phase."""
    sc = _scope()
    if sc is not None:
        return sc.last
    return _LAST_REGION[0]


def bump(name: str) -> None:
    """Count an event in the phase table without timing it (recovery
    events, exec/recovery): shows up in :func:`snapshot` with s=0 and the
    occurrence count, mirrored into the metrics registry
    (``timing_event_<name>``) and — when the flight recorder is armed —
    the trace timeline.  Unconditional — recovery events are rare and
    must be countable even without ``CYLON_TPU_BENCH``."""
    acc = _ACCUM.setdefault(name, [0.0, 0])
    acc[1] += 1
    _EVENT_COUNTS[name] = _EVENT_COUNTS.get(name, 0) + 1
    tr = _TRACE[0]
    if tr is not None:
        tr.instant(name)
    sc = _scope()
    if sc is not None:
        sc._add(name, 0.0)


# Registry-backed attribution tables (cylon_tpu.obs.metrics — the typed
# registry this module's counters migrated onto).  The dict-like views
# keep every call site verbatim while the values live in (and export
# from) the registry; the collector hands the phase table itself to
# metrics.snapshot() / the periodic JSON snapshots.
from ..obs import metrics as _metrics  # noqa: E402

#: name -> bytes moved, the spill tier's phase attribution: seconds alone
#: cannot say whether ``spill.upload`` is PCIe-bound or dispatch-bound —
#: GB/phase does.  Unconditional like bump(): spill traffic must be
#: attributable even without CYLON_TPU_BENCH.
_BYTES = _metrics.namespace("timing_bytes")

#: bump() occurrence counts, registry-visible for Prometheus exposition
_EVENT_COUNTS = _metrics.namespace("timing_event")

_metrics.register_collector(lambda: {"phases": snapshot()})


def add_bytes(name: str, nbytes: int) -> None:
    """Attribute ``nbytes`` of host↔device traffic to a named phase
    (exec/memory spill/evict/upload); appears as ``b`` in
    :func:`snapshot` entries and as ``timing_bytes_<name>`` in the
    metrics registry."""
    _BYTES[name] = _BYTES.get(name, 0) + int(nbytes)
    _ACCUM.setdefault(name, [0.0, 0])
    tr = _TRACE[0]
    if tr is not None:
        tr.instant(name, {"bytes": int(nbytes)})
    sc = _scope()
    if sc is not None:
        sc._add_bytes(name, nbytes)


def reset() -> None:
    """Zero the phase table, byte/event attribution AND the last-region
    breadcrumb (a fresh profile must not inherit the previous
    workload's final phase as its crash breadcrumb) plus the thread's
    park-exclusion accumulator."""
    _ACCUM.clear()
    _BYTES.clear()
    _EVENT_COUNTS.clear()
    _LAST_REGION[0] = ""
    _SCOPE_TLS.excluded = 0.0


def snapshot() -> dict:
    """{region: {"s": total_seconds, "n": calls[, "b": bytes_moved]}}
    sorted by cost; ``b`` appears only for phases that attributed
    host↔device bytes (:func:`add_bytes`)."""
    out = {}
    for k, v in sorted(_ACCUM.items(), key=lambda kv: -kv[1][0]):
        ent = {"s": round(v[0], 4), "n": v[1]}
        if _BYTES.get(k):
            ent["b"] = _BYTES[k]
        out[k] = ent
    return out
