"""Logging wrapper — reference ``util/logging.hpp`` (glog wrapper with
``SetLogLevel``, hpp:18-22).

A thin veneer over :mod:`logging` so framework code logs through one
switchable channel: ``log.info/warning/error/debug`` plus
:func:`set_log_level` (accepting glog-style ints 0-3 or names).  Default
level follows ``CYLON_TPU_LOG`` (env) or WARNING, matching the reference's
quiet-by-default behavior.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("cylon_tpu")

_GLOG_LEVELS = {0: logging.INFO, 1: logging.WARNING, 2: logging.ERROR,
                3: logging.CRITICAL}


def set_log_level(level) -> None:
    """glog-style int (0=INFO..3=FATAL), a logging level int, or a name.

    Rejects bools explicitly: ``bool`` is an ``int`` subclass, so ``True``
    would silently resolve as glog level 1 (WARNING) — almost certainly a
    caller bug (``set_log_level(verbose)``), not a level choice."""
    if isinstance(level, bool):
        raise TypeError(
            "set_log_level expects a glog int (0-3), logging int, or level "
            f"name — got {level!r} (bool would alias glog level {int(level)})")
    if isinstance(level, str):
        lv = getattr(logging, level.upper())
    elif level in _GLOG_LEVELS:
        lv = _GLOG_LEVELS[level]
    else:
        lv = int(level)
    log.setLevel(lv)


if not log.handlers:  # one stderr handler, rank-tagged when multi-process
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter(
        "[%(levelname).1s cylon_tpu %(asctime)s] %(message)s", "%H:%M:%S"))
    log.addHandler(_h)
    set_log_level(os.environ.get("CYLON_TPU_LOG", "WARNING"))
