"""Host materialization that works in BOTH execution modes.

Single-controller (one process drives the whole mesh): ``np.asarray`` sees
every shard.  Multi-controller (``jax.distributed`` SPMD — the reference's
``mpirun -np N`` launch model, README.md:69-73): each process only
addresses its local shards, so sidecar pulls (count matrices, valid-count
vectors, splitter samples) must cross-gather with
``multihost_utils.process_allgather`` before they are host-visible.  Every
host pull of a possibly-sharded device array in the framework goes through
:func:`host_array` so the same operator code runs in either mode.
"""

from __future__ import annotations

import numpy as np


def _sanctioned_pull(kind: str):
    """The DOCUMENTED device→host boundary: every framework host pull runs
    inside this scope, so test sessions can run under
    ``jax.transfer_guard_device_to_host("disallow")``
    (``CYLON_TPU_TRACECHECK=1``) and still permit the sidecar pulls this
    module funnels — any implicit D2H transfer *outside* this funnel is a
    trace-safety violation.  Also feeds the per-op transfer ledger
    (:func:`cylon_tpu.analysis.runtime.note_transfer`, rule RT303)."""
    import jax
    from ..analysis import runtime
    runtime.note_transfer(kind)
    return jax.transfer_guard_device_to_host("allow")


def host_array(x) -> np.ndarray:
    """Materialize a (possibly multi-host row-sharded) array on this host."""
    if isinstance(x, np.ndarray):
        return x
    import jax
    if jax.process_count() > 1 and not getattr(x, "is_fully_addressable",
                                               True):
        from jax.experimental import multihost_utils
        with _sanctioned_pull("host_array"):
            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    with _sanctioned_pull("host_array"):
        return np.asarray(x)


def host_arrays(xs) -> list:
    """Batched :func:`host_array`: ONE overlapped fetch for many device
    arrays.  The axon tunnel charges ~100 ms latency per FIRST fetch of
    each buffer when pulled sequentially; ``jax.device_get`` issues every
    copy async before blocking, collapsing N round-trips into ~one
    (measured v5e tunnel: 20 buffers 3.0 s sequential → 0.14 s batched).
    Entries may be numpy arrays or None (passed through)."""
    import jax
    if jax.process_count() > 1:
        return [None if x is None else host_array(x) for x in xs]
    devs = [x for x in xs if x is not None and not isinstance(x, np.ndarray)]
    with _sanctioned_pull("host_arrays"):
        fetched = iter(jax.device_get(devs))
    return [x if x is None or isinstance(x, np.ndarray) else next(fetched)
            for x in xs]


def host_shard_blocks(x, world: int) -> list:
    """Per-shard host blocks of a row-sharded array WITHOUT any
    cross-process collective: each process pulls only its ADDRESSABLE
    shards (entries for remote shards stay None).  This is the spill
    tier's eviction transport (cylon_tpu.exec.memory): collective-free
    by construction, so a rank whose eviction candidates momentarily
    diverge from its peers' (GC timing) cannot hang the mesh the way a
    ``process_allgather``-based pull would.  Numpy inputs pass through
    as a single block."""
    if isinstance(x, np.ndarray):
        return [x]
    per = x.shape[0] // world
    blocks: list = [None] * world
    with _sanctioned_pull("host_shards"):
        for sh in x.addressable_shards:
            i = (sh.index[0].start or 0) // per
            blocks[i] = np.asarray(sh.data)
    return blocks


_pull_fn = None


def sync_pull(arr) -> None:
    """Force execution of everything feeding ``arr`` and wait.

    ``jax.block_until_ready`` is unreliable over the axon tunnel — a tiny
    jitted reduction pulled to the host is the only real barrier.  Shared by
    the bench drivers (bench.py, scripts/*) so the barrier technique lives
    in one place."""
    global _pull_fn
    import jax
    import jax.numpy as jnp
    if _pull_fn is None:
        from .cache import jit
        _pull_fn = jit(
            lambda x: x.reshape(-1)[:4].astype(jnp.float32).sum())
    with _sanctioned_pull("sync_pull"):
        np.asarray(_pull_fn(arr))
