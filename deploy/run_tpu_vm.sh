#!/usr/bin/env bash
# SPMD launcher for Cloud TPU VMs — the reference's mpirun/jsrun analog
# (summit/scripts/*.lsf, rivanna/scripts/*.slurm): sync the repo to every
# worker of a TPU VM (or pod slice) and run the same command on all of
# them.  Usage: deploy/run_tpu_vm.sh <tpu-name> <zone> "<command>"
set -euo pipefail

TPU_NAME="${1:?tpu name}"
ZONE="${2:?zone}"
CMD="${3:?command to run}"
REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)"

# clear any previous sync first: scp into an EXISTING directory would nest
# the new copy inside it and silently keep running the stale first sync
gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone="$ZONE" --worker=all \
  --command="rm -rf ~/cylon_tpu_run"
gcloud compute tpus tpu-vm scp --recurse "$REPO_DIR" \
  "$TPU_NAME":~/cylon_tpu_run --zone="$ZONE" --worker=all

# every worker runs the same script — multi-host slices form the world
# via jax.distributed.initialize() (TPUConfig(distributed=True))
gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone="$ZONE" --worker=all \
  --command="cd ~/cylon_tpu_run && pip -q install -e . && $CMD"
